"""Multi-tenant serving host: many policy bundles, one process, one budget.

A production serve fleet does not run one process per policy — it packs
many small policies (per desk, per product, per cohort) into each process
and shares the device between them. This module is that packing layer on
top of the continuous batcher:

- **tenants** — each a policy (bundle directory or in-memory
  ``PolicyBundle``/``PipelineResult``) served by its own
  :class:`~orp_tpu.serve.batcher.MicroBatcher` + ``HedgeEngine``, with its
  own optional :class:`~orp_tpu.guard.GuardPolicy` (deadlines, watermark,
  retries keep their exact single-tenant semantics — the host never
  reaches into a tenant's batcher).
- **LRU engine cap** — at most ``max_live_engines`` tenants keep a live
  engine (and its deserialized AOT bucket executables, the real memory
  cost: one PJRT executable per bucket per tenant). Submitting to a cold
  tenant activates it and, over the cap, evicts the least-recently-used
  one: its batcher drains (guard sheds still apply during the drain), its
  engine — executables included — is dropped, and the next submit rebuilds
  it from the retained source (``serve/tenant_evict`` counts evictions;
  an AOT bundle re-activates with zero XLA compiles, which is what makes
  the LRU cheap enough to be a cap rather than a crash).
- **quotas / backpressure** — ``max_pending`` per tenant bounds its
  in-flight requests; past it, submits are shed immediately with a
  structured :class:`~orp_tpu.guard.Rejection` ``reason="quota"`` through
  the future (``guard/shed{reason="quota", tenant=...}``) — one tenant's
  burst cannot starve another's batcher, and the response shape is the
  same one the deadline/watermark sheds already taught clients to handle.
- **SLO burn rate** — per-tenant served-latency objectives evaluated
  straight off the obs registry histograms the metrics façade already
  publishes (``serve_request_latency_seconds{tenant=...}``):
  ``burn_rate = violation_fraction / error_budget``, the standard
  error-budget consumption ratio (>1 means the budget is burning faster
  than it accrues; alert). No second bookkeeping path — the Dapper spine
  (PR 4) records, the host reads.
- **model health** (``orp_tpu/obs/quality.py``) — a tenant whose bundle
  carries a baked training-feature sketch gets a per-tenant
  :class:`~orp_tpu.obs.quality.DriftMonitor`: the columnar block lane
  folds each ADMITTED block into a vectorized online sketch (one update
  per block, never per row) and publishes
  ``quality/drift_score{tenant,feature}`` through the same registry the
  scrape plane serves; a band breach emits ``quality/drift_trip`` and a
  flight-recorder TRIP. :meth:`ServeHost.reload_tenant` grows the
  QUANTITATIVE canary gate (``quality_band=``): candidate and incumbent
  replay the bundle's pinned validation scenario set off-traffic, and a
  hedge-error regression outside the band rejects exactly like a bitwise
  canary failure — while every verdict (promote AND reject) appends to the
  hash-linked promotions chain (``obs.chain_append``/``chain_verify``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
import warnings

import numpy as np

from orp_tpu.guard import inject as _inject
from orp_tpu.guard.serve import GuardPolicy, Rejection
from orp_tpu.obs import count as obs_count
from orp_tpu.obs import flight
from orp_tpu.obs import observe as obs_observe
from orp_tpu.obs import state as obs_state
from orp_tpu.obs.registry import Registry
from orp_tpu.serve.batcher import MicroBatcher, SlimFuture
from orp_tpu.serve.engine import HedgeEngine
from orp_tpu.serve.metrics import LATENCY_HISTOGRAM, ServingMetrics
from orp_tpu.store.tier import TierManager


@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """A served-latency objective with an error budget.

    ``latency_slo_ms`` — the per-request latency objective (submit to
    resolved, device-complete — the ``ServingMetrics`` clock).
    ``error_budget``  — the tolerated fraction of requests over the
    objective (SRE convention: 0.01 = 99% of requests in SLO).
    """

    latency_slo_ms: float
    error_budget: float = 0.01

    def __post_init__(self):
        if self.latency_slo_ms <= 0:
            raise ValueError(
                f"latency_slo_ms={self.latency_slo_ms} must be > 0")
        if not 0.0 < self.error_budget <= 1.0:
            raise ValueError(
                f"error_budget={self.error_budget} must be in (0, 1]")


def burn_rate(histogram, slo: SloPolicy) -> float:
    """Error-budget consumption ratio of a latency histogram (seconds)
    against ``slo``: observed violation fraction / budget. 1.0 = burning
    exactly at budget; > 1 = the objective will be missed over the window."""
    return histogram.fraction_over(slo.latency_slo_ms / 1e3) / slo.error_budget


class CanaryRejected(RuntimeError):
    """A hot bundle reload failed its canary gate: the candidate engine did
    not reproduce the serving tenant's pinned probe rows, went non-finite,
    or regressed past the hedge-error quality band on the pinned validation
    set. The tenant was NOT touched — it keeps serving the old bundle's
    bits; the reject is the rollback."""


#: tenants already warned about a finiteness-only promotion path
#: (``require_same_bits=False`` with no ``quality_band``) — warn ONCE per
#: tenant per process; the ``guard/canary_unguarded`` counter fires every
#: time
_UNGUARDED_WARNED: set = set()


class _Tenant:
    """One hosted policy: retained source + (while live) engine/batcher."""

    __slots__ = ("name", "source", "policy", "max_pending", "slo",
                 "engine", "batcher", "metrics", "pending", "activations",
                 "last_used", "build_lock", "in_submit", "version",
                 "drift", "drift_band", "warm", "precision")

    def __init__(self, name, source, policy, max_pending, slo, drift_band,
                 precision=None):
        self.name = name
        self.source = source          # bundle dir (str/Path) or policy object
        self.warm = None              # warm tier: the DESERIALIZED policy,
        # retained across evictions (tier.py bounds how many tenants keep it)
        self.policy = policy
        self.max_pending = max_pending
        self.slo = slo
        self.engine = None
        self.batcher = None
        self.metrics = None
        self.pending = 0              # futures submitted and not yet resolved
        self.activations = 0
        self.last_used = 0.0
        self.in_submit = 0            # submits between claim and enqueue —
        # eviction never unlinks a tenant mid-submit (host-lock guarded)
        self.version = 1              # bumped by every canary-passed reload
        # model-health drift monitor (obs/quality.py), built at first
        # activation when the policy carries a baked feature sketch; like
        # metrics it SURVIVES eviction — the sketch describes the tenant's
        # traffic, not one engine incarnation
        self.drift = None
        self.drift_band = drift_band
        # serving precision tier (serve/precision.py): None = the host
        # engine_kwargs' default (f32). Survives eviction — a tenant
        # promoted to bf16 through the quality band re-activates at bf16
        self.precision = precision
        # serializes THIS tenant's engine build without the host lock: a
        # cold start (bundle load + engine construction + possible jit
        # compiles) must never head-of-line-block other tenants' submits
        self.build_lock = threading.Lock()


class ServeHost:
    """Serve many policies from one process under an engine-memory cap.

    ``max_live_engines`` — LRU cap on simultaneously-live engines (each
    holds its policy's device params and deserialized AOT executables).
    ``registry``         — metrics registry the per-tenant ``ServingMetrics``
    façades intern into (labelled ``tenant=<name>``); defaults to the
    active obs session's registry, else a private one. ``slo_report``
    reads the same histograms back — one spine, no side bookkeeping.
    ``engine_kwargs`` / ``batcher_kwargs`` apply to every tenant's engine /
    batcher (per-tenant overrides via ``add_tenant``).
    """

    def __init__(self, *, max_live_engines: int = 4,
                 registry: Registry | None = None,
                 engine_kwargs: dict | None = None,
                 batcher_kwargs: dict | None = None,
                 promotion_chain=None,
                 tiers: TierManager | None = None):
        if max_live_engines < 1:
            raise ValueError(
                f"max_live_engines={max_live_engines} must be >= 1")
        self.max_live_engines = int(max_live_engines)
        # hot/warm/cold tier bookkeeping (orp_tpu/store/tier.py): eviction
        # demotes hot->warm (the deserialized policy is retained for a
        # zero-compile rebuild) instead of dropping everything; pass a
        # configured TierManager to bound warm retention differently
        self.tiers = tiers if tiers is not None else TierManager()
        # the promotions manifest chain (obs/manifest.py) reload_tenant
        # appends its verdicts to; None = resolve per reload from the active
        # telemetry session's export dir (still None -> no chain, verdicts
        # observable via counters/flight only)
        self.promotion_chain = promotion_chain
        st = obs_state()
        self.registry = (registry if registry is not None
                         else st.registry if st is not None else Registry())
        self.engine_kwargs = dict(engine_kwargs or {})
        self.batcher_kwargs = dict(batcher_kwargs or {})
        self._lock = threading.RLock()
        # rides the host lock: reload's atomic swap waits on it for a
        # tenant's in-flight submit claims to clear (notified by submit's
        # release path when a tenant's count hits zero)
        self._swap_cv = threading.Condition(self._lock)
        # pending counts live under their OWN lock: future done-callbacks
        # fire on the batcher worker thread, and an eviction drains that
        # worker while holding the host lock — a callback that needed the
        # host lock would stall the very drain waiting on it
        self._pending_lock = threading.Lock()
        self._tenants: dict[str, _Tenant] = {}
        self._closed = False

    # -- tenant lifecycle ----------------------------------------------------

    def add_tenant(self, name: str, source, *,
                   policy: GuardPolicy | None = None,
                   max_pending: int | None = None,
                   slo: SloPolicy | None = None,
                   drift_band: float | None = None,
                   precision: str | None = None) -> None:
        """Register a tenant. ``source`` is a bundle directory (loaded
        lazily on first use, reloaded after an eviction) or an in-memory
        policy (``PolicyBundle`` / trained ``PipelineResult`` — retained,
        only the engine is rebuilt). Registration is cheap: no engine is
        built until the first submit. ``drift_band`` overrides the default
        feature-drift trip band (``obs.quality.DEFAULT_DRIFT_BAND``) for a
        policy whose bundle bakes a feature sketch; monitoring is skipped
        entirely for policies without one. ``precision`` pins the tenant's
        serving tier (serve/precision.py; None = the engine default, f32)
        — registering a tenant straight onto a non-f32 tier is the
        operator's call; the guarded route is registering at f32 and
        promoting through ``reload_tenant``'s quality band."""
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending={max_pending} must be >= 1")
        if drift_band is not None and drift_band <= 0:
            raise ValueError(f"drift_band={drift_band} must be > 0")
        with self._lock:
            if self._closed:
                raise RuntimeError("ServeHost is closed")
            if name in self._tenants:
                raise ValueError(f"tenant {name!r} already registered")
            self._tenants[name] = _Tenant(name, source, policy, max_pending,
                                          slo, drift_band, precision)

    def prefetch(self, names) -> list:
        """Predictively warm tenants WITHOUT building engines: each cold
        path/store source is resolved into its deserialized policy and
        retained on the warm tier, so the tenant's first request pays an
        engine build (a warm activation), not a cold directory load.
        Already-live and already-warm tenants are skipped; unknown names
        are ignored (the routing table may know tenants this host was
        never given). The fleet's routing-assignment hook
        (``orp_tpu.store.tier.prefetch_assigned``) drives this; it is also
        directly callable with an expected working set. Returns the names
        actually warmed."""
        warmed = []
        for name in names:
            with self._lock:
                if self._closed:
                    break
                t = self._tenants.get(name)
                if t is None or t.batcher is not None or t.warm is not None:
                    continue
            with t.build_lock:
                with self._lock:
                    if t.batcher is not None or t.warm is not None:
                        continue
                source = t.source
                if (isinstance(source, (str, bytes))
                        or hasattr(source, "__fspath__")):
                    from orp_tpu.serve.bundle import load_bundle

                    source = load_bundle(source)
                with self._lock:
                    if t.batcher is not None:
                        continue  # an activation won the race; already hot
                    t.warm = source
                for cold_name in self.tiers.note_warm(name):
                    with self._lock:
                        other = self._tenants.get(cold_name)
                        if other is not None and other.engine is None:
                            other.warm = None
                obs_count("store/prefetch", tenant=name)
            warmed.append(name)
        return warmed

    def _engine_kwargs_for(self, t) -> dict:
        """Host-wide engine kwargs plus the tenant's pinned serving tier
        (``serve/precision.py``). ``t.precision is None`` means the host
        default — usually f32 — so the dict is returned untouched and an
        old-style host behaves bit-for-bit as before."""
        if t.precision is None:
            return self.engine_kwargs
        return {**self.engine_kwargs, "precision": t.precision}

    def _activate(self, name: str):
        """Touch ``name`` in the LRU, building its engine/batcher if cold.
        Returns ``(tenant, batcher, evicted_batchers)``. Called WITHOUT the
        host lock held: the build (bundle load + engine construction +
        possible jit compiles — seconds on a cold jit bundle) runs under
        the tenant's OWN lock so other tenants' submits never queue behind
        one tenant's cold start. Over-cap victims are UNLINKED under the
        host lock but their batchers are returned for the caller to drain
        outside every lock (a drain runs client done-callbacks, and a
        callback may re-enter the host)."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                raise KeyError(f"unknown tenant {name!r}; registered: "
                               f"{sorted(self._tenants)}")
            t.last_used = time.perf_counter()
            if t.batcher is not None:
                # sweep HERE too, not only after a build: a build-time
                # sweep that found every candidate mid-submit would
                # otherwise leave the cap exceeded forever
                return t, t.batcher, self._sweep_locked(t)
        evicted = []
        with t.build_lock:
            with self._lock:
                batcher = t.batcher
            if batcher is None:
                t_build = time.perf_counter()
                # tier ladder: a retained deserialized policy (warm) skips
                # the directory load entirely — the engine rebuild hits the
                # process-wide jit executable cache / the bundle's AOT
                # blobs, so a warm re-activation costs zero XLA compiles.
                # An in-memory source (PolicyBundle passed to add_tenant)
                # is warm by construction; only a path source without a
                # retained policy pays the cold load. Snapshot under the
                # host lock: _unlink clears other tenants' warm refs under
                # it, and build_lock alone does not exclude that writer.
                with self._lock:
                    source = t.warm
                tier = "warm"
                if source is None:
                    source = t.source
                    if (isinstance(source, (str, bytes))
                            or hasattr(source, "__fspath__")):
                        from orp_tpu.serve.bundle import load_bundle

                        tier = "cold"
                        source = load_bundle(source)
                engine = HedgeEngine(source, **self._engine_kwargs_for(t))
                metrics = ServingMetrics(registry=self.registry,
                                         labels={"tenant": t.name})
                drift = t.drift
                if drift is None:
                    drift = self._build_drift(t, source)
                batcher = MicroBatcher(engine, metrics=metrics,
                                       policy=t.policy, **self.batcher_kwargs)
                with self._lock:
                    if self._closed:
                        # a close() raced the build: never install a live
                        # worker on a closed host
                        batcher.close()
                        raise RuntimeError("ServeHost is closed")
                    t.engine = engine
                    t.metrics = metrics
                    t.drift = drift
                    t.batcher = batcher
                    t.warm = source
                    t.activations += 1
                    evicted = self._sweep_locked(t)
                self.tiers.note_hot(t.name)
                obs_count("serve/tenant_activate", tenant=t.name, tier=tier)
                obs_observe("serve/activation_seconds",
                            time.perf_counter() - t_build, tier=tier)
        return t, batcher, evicted

    def _build_drift(self, t: _Tenant, policy):
        """The one definition of a tenant's drift monitor: built from the
        policy's baked feature sketch (None without one — monitoring is
        skipped, never faked), banded by the tenant's ``drift_band``
        override, publishing into the host registry the scrape plane
        serves. Shared by cold activation and hot reload so the two paths
        can never configure monitors differently."""
        sketch = getattr(policy, "feature_sketch", None)
        if sketch is None:
            return None
        from orp_tpu.obs.quality import DEFAULT_DRIFT_BAND, DriftMonitor

        return DriftMonitor(
            sketch,
            band=(t.drift_band if t.drift_band is not None
                  else DEFAULT_DRIFT_BAND),
            registry=self.registry, tenant=t.name)

    def _sweep_locked(self, current: _Tenant) -> list:
        """Unlink LRU tenants until the live-engine count is back at the
        cap; returns their batchers for an out-of-lock drain. Caller holds
        the host lock. Never unlinks ``current`` or a tenant mid-submit
        (an in-flight claim would enqueue on the closed batcher) — if
        every candidate is busy the cap is exceeded transiently (a soft
        cap beats a raced RuntimeError) and the next activation sweeps
        again."""
        evicted = []
        live = [x for x in self._tenants.values() if x.batcher is not None]
        while len(live) > self.max_live_engines:
            idle = [x for x in live if x is not current and x.in_submit == 0]
            if not idle:
                break
            victim = min(idle, key=lambda x: x.last_used)
            evicted.append(self._unlink(victim))
            live.remove(victim)
        return evicted

    def _unlink(self, t: _Tenant):
        """Detach ``t``'s serving state under the host lock (new submits
        now rebuild) and hand its batcher back for an out-of-lock drain:
        the queue finishes with guard sheds still applying — a deadline
        that expires during the drain is still a structured Rejection —
        then the engine and its deserialized AOT executables are released.
        The tenant stays registered."""
        batcher = t.batcher
        t.batcher = None
        t.engine = None
        # t.metrics stays: the façade interns shared-registry series, so a
        # reactivation accumulates into the same instruments and stats()
        # keeps reporting what an evicted tenant served
        # hot -> WARM, not cold: t.warm keeps the deserialized policy so
        # re-activation is an engine rebuild (zero XLA compiles), not a
        # directory re-read. Past the tier manager's warm cap the
        # longest-idle warm tenants genuinely go cold — their retained
        # policies are released here
        if t.warm is not None:
            for cold_name in self.tiers.note_warm(t.name):
                other = self._tenants.get(cold_name)
                if other is not None and other.engine is None:
                    other.warm = None
        else:
            self.tiers.note_cold(t.name)
        obs_count("serve/tenant_evict", tenant=t.name,
                  tier=self.tiers.tier_of(t.name))
        return batcher

    # -- request path --------------------------------------------------------

    def _claim_batcher(self, name: str):
        """Activate ``name`` and CLAIM its live batcher: ``(tenant,
        batcher)`` with ``in_submit`` already incremented (the token that
        makes the batcher un-evictable); the caller MUST release via
        :meth:`_release_claim` once its enqueue is done.

        Claim loop: between activation and the claim a concurrent
        activation may LRU-evict this tenant (its batcher closes); a failed
        claim just re-activates. Bounded: a freshly-activated tenant loses
        the race only to an eviction that slipped between the two locks.
        Evicted victims drain HERE, outside every lock (the drain resolves
        futures, and a done-callback may re-enter the host)."""
        for _ in range(16):
            with self._lock:
                if self._closed:
                    raise RuntimeError("ServeHost is closed")
            t, batcher, evicted = self._activate(name)
            with self._lock:
                claimed = t.batcher is batcher and batcher is not None
                if claimed:
                    t.in_submit += 1
            for victim in evicted:
                victim.close()
            if claimed:
                return t, batcher
        # pragma: no cover - needs pathological eviction churn
        raise RuntimeError(
            f"tenant {name!r}: could not claim a live batcher "
            "(eviction churn; raise max_live_engines)")

    def _release_claim(self, t: _Tenant) -> None:
        with self._lock:
            t.in_submit -= 1
            if t.in_submit == 0:
                # a reload swap may be parked on this count (notify on
                # the shared host lock: nanoseconds with no waiters)
                self._swap_cv.notify_all()

    def submit(self, tenant: str, date_idx: int, states, prices=None, *,
               deadline_s: float | None = None):
        """Route one request to ``tenant``'s batcher; returns its future
        (``(phi, psi, value)``, or a :class:`Rejection` — the tenant's own
        guard sheds plus the host's ``reason="quota"``)."""
        t, batcher = self._claim_batcher(tenant)
        try:
            with self._pending_lock:
                over = (t.max_pending is not None
                        and t.pending >= t.max_pending)
                if not over:
                    t.pending += 1
            if over:
                # over quota: shed NOW, at zero queue age — the point of a
                # quota is that the request never consumes batcher capacity
                obs_count("guard/shed", reason="quota", tenant=t.name)
                fut = SlimFuture()
                fut.set_result(Rejection(reason="quota", queued_s=0.0,
                                         deadline_s=deadline_s))
                return fut
            try:
                fut = batcher.submit(date_idx, states, prices,
                                     deadline_s=deadline_s)
            except BaseException:
                self._request_done(t)  # the slot was reserved, never used
                raise
            fut.add_done_callback(lambda _f, _t=t: self._request_done(_t))
            return fut
        finally:
            self._release_claim(t)

    def submit_block(self, tenant: str, date_idx: int, states, prices=None,
                     deadlines=None, *, trace=None):
        """Columnar ingest lane through the host: one
        :meth:`~orp_tpu.serve.batcher.MicroBatcher.submit_block` per block,
        ONE future, quota counted in ROWS against the tenant's
        ``max_pending`` budget. Rows past the remaining budget are shed as
        a TAIL SLICE — status :data:`~orp_tpu.serve.ingest.SHED_QUOTA` in
        the returned :class:`~orp_tpu.serve.ingest.BlockResult`, zero queue
        age, never a per-row ``Rejection`` — and only the head rows consume
        batcher capacity. (The per-request lane counts the same budget in
        requests; a mixed tenant's ``pending`` is requests + block rows.)
        ``trace`` is the optional distributed-trace context, passed through
        to the batcher untouched (a quota-split block's admitted head
        carries it; the merged result keeps its server timing)."""
        from orp_tpu.serve.ingest import (SHED_QUOTA, all_shed_result,
                                          merge_tail_shed)

        feats = np.atleast_2d(np.ascontiguousarray(states))
        n = feats.shape[0]
        pr = (np.atleast_2d(np.ascontiguousarray(prices))
              if prices is not None else None)
        t, batcher = self._claim_batcher(tenant)
        try:
            with self._pending_lock:
                keep = (n if t.max_pending is None
                        else max(0, min(n, t.max_pending - t.pending)))
                t.pending += keep
            n_quota = n - keep
            if n_quota:
                obs_count("guard/shed", n_quota, reason="quota",
                          tenant=t.name, lane="block")
            if keep and t.drift is not None:
                # model-health sketch: ONE vectorized fold of the admitted
                # head per block (never per row — the monitoring twin of
                # the ORP013 discipline); the drift_overhead bench phase
                # gates this bill ≤ 5% of the columnar lane. FAIL-OPEN: a
                # monitor error must never break the submit path (the
                # pending quota above is already reserved, and serving
                # outranks observing)
                try:
                    t.drift.update(feats[:keep])
                except Exception:  # orp: noqa[ORP009] -- counted below; monitoring is advisory and must never take down the ingest lane
                    obs_count("quality/drift_monitor_error", tenant=t.name)
            if keep == 0:
                fut = SlimFuture()
                fut.set_result(all_shed_result(
                    n, SHED_QUOTA, has_value=pr is not None,
                    dtype=feats.dtype if feats.dtype.kind == "f"
                    else np.float32))
                return fut
            dl = deadlines
            if dl is not None and np.ndim(dl) == 1:
                dl = np.asarray(dl)[:keep]  # the admitted head's budgets
            try:
                inner = batcher.submit_block(
                    date_idx, feats[:keep],
                    None if pr is None else pr[:keep], dl, trace=trace)
            except BaseException:
                self._rows_done(t, keep)  # reserved rows, never enqueued
                raise
            if n_quota == 0:
                inner.add_done_callback(
                    lambda _f, _t=t, _k=keep: self._rows_done(_t, _k))
                return inner
            # partial admission: the caller's future must still describe
            # ALL n rows — append the quota-shed tail to the head's result
            outer = SlimFuture()

            def _forward(f, _t=t, _k=keep, _tail=n_quota):
                self._rows_done(_t, _k)
                exc = f.exception()
                if exc is not None:
                    outer.set_exception(exc)
                else:
                    outer.set_result(
                        merge_tail_shed(f.result(), _tail, SHED_QUOTA))

            inner.add_done_callback(_forward)
            return outer
        finally:
            self._release_claim(t)

    def _request_done(self, t: _Tenant) -> None:
        with self._pending_lock:
            t.pending -= 1

    def _rows_done(self, t: _Tenant, k: int) -> None:
        with self._pending_lock:
            t.pending -= k

    # -- hot reload ----------------------------------------------------------

    def reload_tenant(self, name: str, source=None, *, canary_rows: int = 8,
                      require_same_bits: bool = True,
                      quality_band: float | None = None,
                      validation=None,
                      precision: str | None = None) -> dict:
        """Versioned hot bundle swap with a canary gate; the tenant never
        stops serving.

        ``source`` — the candidate bundle dir / in-memory policy (None =
        reload the tenant's CURRENT source: the artifact-refresh shape,
        e.g. a re-export that added AOT sets). The candidate engine is
        built OFF-TRAFFIC and must reproduce the serving engine's pinned
        probe rows — ``canary_rows`` deterministic feature rows at the
        first and last rebalance dates, BITWISE (the serve forward is
        deterministic per policy, so any flipped bit is a wrong candidate:
        corrupted params, foreign bundle, broken artifact) — before it
        takes traffic. A candidate that fails raises
        :class:`CanaryRejected` and emits ``guard/canary_reject``; the
        tenant keeps serving the old bundle's bits untouched (the reject IS
        the rollback — nothing was swapped).

        ``require_same_bits=False`` relaxes the bitwise pin — the knob for
        rolling a genuinely RETRAINED policy, where different bits are the
        point. Alone it leaves only the finiteness check, which accepts ANY
        finite policy however wrong its hedges — so doing it without a
        ``quality_band`` warns once per tenant and emits
        ``guard/canary_unguarded`` (the silently-relaxed gate is now
        observable).

        ``quality_band`` — the QUANTITATIVE acceptance gate: candidate and
        incumbent each replay the pinned validation scenario set
        (``validation=`` or the candidate bundle's baked
        ``ValidationSpec``) OFF-TRAFFIC through
        :func:`orp_tpu.obs.quality.evaluate_quality` — same scrambles for
        both, so the comparison is paired and Monte-Carlo noise cancels —
        and a candidate whose aggregate hedge error regresses more than
        ``quality_band`` (relative: 0.05 = +5%) is rejected
        (``guard/canary_reject{stage="quality"}``) with the incumbent's
        bits untouched. This is the gate a retrained policy must pass:
        different bits allowed, worse hedging not.

        Every verdict — promote and reject — appends to the promotions
        manifest chain (``obs.chain_append``; ``promotion_chain`` ctor arg,
        else the active telemetry session's bundle dir), so the serving
        history is an auditable hash-linked ledger.

        ``precision`` — promote the tenant to a serving tier
        (``serve/precision.py``: "f32" | "bf16" | "int8"; None = keep the
        tenant's current tier). A tier change produces DIFFERENT bits by
        construction, so it is refused under ``require_same_bits=True``:
        the supported route is ``require_same_bits=False`` with a
        ``quality_band``, which replays the pinned validation set on the
        f32-equivalent INCUMBENT versus the reduced-precision candidate —
        paired scrambles, so the measured regression is the tier's
        quantisation error, not Monte-Carlo noise. On promotion the tier
        is pinned on the tenant and survives eviction/re-activation.

        On a pass: the new batcher is installed atomically (the swap waits
        for in-flight submit claims, so no request lands on a dead
        batcher), the old one drains OUTSIDE every lock — queued requests
        still resolve through the old engine, shed policies still apply —
        and the tenant's version bumps (``serve/bundle_swap``).
        """
        if quality_band is not None and quality_band < 0:
            raise ValueError(f"quality_band={quality_band} must be >= 0 "
                             "(0 = no regression tolerated at all)")
        if validation is not None and quality_band is None:
            # the caller clearly wants the quality gate — dropping their
            # validation set silently and promoting on finiteness alone is
            # exactly the surprise this gate exists to remove
            raise ValueError(
                "validation= was passed without quality_band= — the "
                "validation set is only consumed by the quality gate; pass "
                "quality_band=<max relative hedge-error regression> to arm "
                "it")
        if precision is not None:
            from orp_tpu.serve.precision import normalize_precision

            normalize_precision(precision)  # unknown tier: fail before work
        with self._lock:
            if name not in self._tenants:
                raise KeyError(f"unknown tenant {name!r}; registered: "
                               f"{sorted(self._tenants)}")
        if not require_same_bits and quality_band is None:
            # the finiteness-only promotion path: legal (a retrain may have
            # no validation set yet) but no longer SILENT — the gate that
            # accepts any finite policy is itself an observable event
            obs_count("guard/canary_unguarded", tenant=name)
            flight.record("canary_unguarded", tenant=name)
            if name not in _UNGUARDED_WARNED:
                _UNGUARDED_WARNED.add(name)
                warnings.warn(
                    f"reload_tenant({name!r}, require_same_bits=False) "
                    "without a quality_band: the canary gate is relaxed to "
                    "FINITENESS ONLY — any finite candidate passes, however "
                    "wrong its hedge ratios. Pass quality_band= (the "
                    "hedge-error regression gate over the bundle's pinned "
                    "validation set) for retrained policies",
                    stacklevel=2,
                )
        # the OLD engine's bits are the canary pin: activate if cold, then
        # CLAIM the tenant (in_submit, the same token a submit holds) so a
        # concurrent activation's LRU sweep cannot evict it — and null
        # t.engine — between the activation and the probe evaluations.
        # Bounded like submit's claim loop: the only way to lose is an
        # eviction slipping between the two locks.
        for _ in range(16):
            t, batcher_live, evicted = self._activate(name)
            with self._lock:
                claimed = t.batcher is batcher_live and t.engine is not None
                if claimed:
                    t.in_submit += 1
                    old_engine = t.engine
            for victim in evicted:
                victim.close()  # outside every lock, as always
            if claimed:
                break
        else:  # pragma: no cover - needs pathological eviction churn
            raise RuntimeError(
                f"tenant {name!r}: could not pin a live engine for the "
                "canary (eviction churn; raise max_live_engines)")
        try:
            nf = old_engine.model.n_features
            # deterministic probe rows near the training normalisation;
            # first and last dates catch a torn per-date params axis at
            # both ends
            probe = (1.0 + 0.05 * np.random.default_rng(7)
                     .standard_normal((int(canary_rows), nf))
                     ).astype(np.float32)
            dates = sorted({0, old_engine.n_dates - 1})
            pinned = [old_engine.evaluate(d, probe) for d in dates]
        finally:
            # release BEFORE the candidate build + swap: the swap below
            # waits for in_submit to clear, and holding our own claim
            # across it would deadlock on ourselves
            with self._lock:
                t.in_submit -= 1
                if t.in_submit == 0:
                    self._swap_cv.notify_all()
        if (precision is not None and require_same_bits
                and precision != old_engine.precision.tier):
            raise ValueError(
                f"tenant {name!r}: precision={precision!r} changes the "
                f"serving tier (incumbent {old_engine.precision.tier!r}) — "
                "different bits by construction, so the bitwise canary can "
                "never pass. Promote tiers with require_same_bits=False and "
                "a quality_band (the paired hedge-error gate)")
        # load + build the candidate OUTSIDE every host lock (a reload must
        # never head-of-line-block serving; the ORP012 discipline)
        new_source = t.source if source is None else source
        policy = new_source
        if (isinstance(policy, (str, bytes))
                or hasattr(policy, "__fspath__")):
            from orp_tpu.serve.bundle import load_bundle

            try:
                policy = load_bundle(policy)
            except (ValueError, OSError) as e:
                self._canary_reject(
                    name, f"candidate bundle failed to load ({e})",
                    stage="load", cause=e)
        quality = None
        spec = None
        if quality_band is not None:
            spec = validation if validation is not None else getattr(
                policy, "validation", None)
            if spec is None:
                raise ValueError(
                    f"tenant {name!r}: quality_band={quality_band} needs a "
                    "pinned validation set — pass validation="
                    "ValidationSpec(...) or re-export the candidate bundle "
                    "with the current code (`orp export` bakes one)")
        inj = _inject.active()
        if inj is not None:
            # chaos harness (guard/inject.py): bundle corruption mid-reload
            # — the bytes passed every on-disk digest, the in-memory object
            # is wrong; the canary below is the only gate left
            policy = inj.corrupt_policy(policy)
        cand_kwargs = self._engine_kwargs_for(t)
        if precision is not None:
            cand_kwargs = {**cand_kwargs, "precision": precision}
        with t.build_lock:  # orp: noqa[ORP012] -- build_lock is the per-tenant BUILD serializer (vs a racing activation), not a batcher/host lock; nothing drains or serves under it
            engine = HedgeEngine(policy, **cand_kwargs)
            for d, (pphi, ppsi, _pv) in zip(dates, pinned):
                phi, psi, _v = engine.evaluate(d, probe)
                if not (np.isfinite(phi).all() and np.isfinite(psi).all()):
                    self._canary_reject(name, f"non-finite outputs at date "
                                              f"{d}", stage="finiteness")
                if require_same_bits and not (
                        np.array_equal(phi, pphi)
                        and np.array_equal(psi, ppsi)):
                    self._canary_reject(
                        name, f"probe bits diverged at date {d} "
                              "(corrupted or foreign candidate)")
        if quality_band is not None:
            from orp_tpu.obs.quality import evaluate_quality

            # OUTSIDE the build lock: the full RQMC replays take seconds,
            # and a concurrent cold re-activation of this tenant serializes
            # on build_lock — only engine construction belongs under it.
            # Both replays run AFTER the cheap gates (load, finiteness,
            # bits) so a candidate they already reject never bills the
            # expensive evaluation. The incumbent publishes its gauges into
            # the live registry (it IS the serving policy); the candidate's
            # go to a THROWAWAY registry — a possibly-rejected candidate's
            # numbers must never land in the live scrape as the tenant's
            # serving series (the chain/exception carry them for audit).
            # The spec usually comes from the CANDIDATE, so a retrain that
            # changed the rebalance grid or feature count fails at the
            # incumbent's evaluation — a failed promotion, recorded like
            # every other verdict
            try:
                inc_rec = evaluate_quality(engine=old_engine, spec=spec,
                                           registry=self.registry,
                                           tenant=name)
            except (ValueError, RuntimeError) as e:
                self._canary_reject(
                    name, "the candidate's pinned validation set does not "
                          f"fit the serving incumbent ({e})",
                    stage="quality", cause=e)
            try:
                cand_rec = evaluate_quality(engine=engine, spec=spec,
                                            registry=Registry())
            except (ValueError, RuntimeError) as e:
                # spec mismatch OR a runtime failure of the candidate's own
                # dispatch (the doctor probe catches the same pair): either
                # way a failed promotion, recorded like every other verdict
                self._canary_reject(
                    name, f"candidate cannot run the pinned validation set "
                          f"({e})", stage="quality", cause=e)
            inc_err = inc_rec["hedge_error"]["mean"]
            cand_err = cand_rec["hedge_error"]["mean"]
            regression = (cand_err - inc_err) / max(inc_err, 1e-12)
            quality = {
                "band": float(quality_band),
                "validation_fingerprint": spec.fingerprint(),
                "incumbent": inc_rec["hedge_error"],
                "candidate": cand_rec["hedge_error"],
                "regression": round(float(regression), 6),
            }
            if regression > quality_band:
                self._canary_reject(
                    name,
                    f"hedge-error regression {regression:+.2%} exceeds "
                    f"the quality band {quality_band:+.2%} (incumbent "
                    f"{inc_err:.6g} -> candidate {cand_err:.6g} ± "
                    f"{cand_rec['hedge_error']['ci95']:.2g} on the "
                    "pinned validation set)",
                    stage="quality", quality=quality)
        # snapshot the live metrics façade under the host lock — _activate
        # installs it under self._lock, and this builder runs outside it
        with self._lock:
            metrics = t.metrics
        batcher = MicroBatcher(engine, metrics=metrics,
                               policy=t.policy, **self.batcher_kwargs)
        # a promoted candidate's baked sketch is the NEW drift baseline (a
        # retrain's training distribution is the reference its serving
        # traffic should be compared against); a sketch-less candidate
        # keeps the old monitor — stale beats blind
        new_drift = self._build_drift(t, policy)
        stalled = False
        evicted2: list = []
        with self._lock:
            if self._closed:
                closed = True
            else:
                closed = False
                # atomic swap: wait out in-flight submit claims so none
                # lands on the batcher being retired (bounded — a claim
                # spans two lock acquisitions, not a request lifetime)
                deadline = time.perf_counter() + 5.0
                while t.in_submit and time.perf_counter() < deadline:
                    self._swap_cv.wait(timeout=0.05)
                if t.in_submit:
                    # a claim outlived the whole wait (pathological stall):
                    # swapping anyway would retire a batcher that claim is
                    # about to enqueue on — refuse LOUDLY and keep serving
                    # the old bundle; the reload is retryable
                    stalled = True
                else:
                    old_batcher = t.batcher
                    t.batcher = batcher
                    t.engine = engine
                    t.source = new_source
                    t.warm = policy  # the retained warm policy must track
                    # the swap — a later warm re-activation serves the NEW
                    # bundle's bits, never a stale pre-swap policy
                    if precision is not None:
                        # tier pin survives eviction: a warm re-activation
                        # rebuilds at the PROMOTED tier, not the default
                        t.precision = precision
                    if new_drift is not None:
                        t.drift = new_drift
                    t.version += 1
                    version = t.version
                    # the tenant may have been EVICTED between the canary
                    # and this swap — installing counts as an activation,
                    # so the cap sweep runs like one
                    evicted2 = self._sweep_locked(t)
        if closed or stalled:
            batcher.close()
            if closed:
                raise RuntimeError("ServeHost is closed")
            obs_count("guard/reload_stalled", tenant=name)
            raise RuntimeError(
                f"tenant {name!r}: an in-flight submit claim outlived the "
                "5s swap window; reload aborted (the tenant keeps serving "
                "the previous bundle — retry the reload)")
        obs_count("serve/bundle_swap", tenant=name)
        if quality is not None:
            # the live quality gauges must describe the SERVING policy:
            # re-publish the promoted candidate's record over the retired
            # incumbent's numbers
            from orp_tpu.obs.quality import publish_quality

            publish_quality(cand_rec, self.registry, tenant=name)
        self._chain_verdict(name, action="promote", version=version,
                            require_same_bits=bool(require_same_bits),
                            source=str(new_source),
                            precision=engine.precision.tier,
                            **({"quality": quality} if quality else {}))
        for victim in (*evicted2, *(() if old_batcher is None
                                    else (old_batcher,))):
            # drain OUTSIDE every lock: the old queue resolves through the
            # old engine (guard sheds still apply), done-callbacks may
            # re-enter the host
            victim.close()
        out = {"tenant": name, "version": version, "swapped": True,
               "canary_rows": int(canary_rows), "canary_dates": dates,
               "require_same_bits": bool(require_same_bits),
               "precision": engine.precision.tier}
        if quality is not None:
            out["quality"] = quality
        return out

    def _chain_path(self):
        """Resolve where promotion verdicts chain to: the ctor arg, else the
        active telemetry session's bundle dir, else nowhere (None)."""
        if self.promotion_chain is not None:
            return self.promotion_chain
        st = obs_state()
        if st is not None and getattr(st, "export_dir", None) is not None:
            import pathlib

            from orp_tpu.obs.manifest import CHAIN_FILE

            return pathlib.Path(st.export_dir) / CHAIN_FILE
        return None

    def _chain_verdict(self, name: str, **record) -> None:
        """Append one promotion verdict to the manifest chain (no-op when
        no chain is configured and no telemetry session exports). A chain
        WRITE failure must never change a reload's outcome — the promote
        path runs after the swap already took traffic, and a reject must
        surface as CanaryRejected, not as the audit log's OSError — so it
        degrades to a warning + counter instead of raising."""
        path = self._chain_path()
        if path is None:
            return
        from orp_tpu.obs.manifest import chain_append

        try:
            chain_append(path, {"tenant": name, **record})
        except OSError as e:
            obs_count("quality/chain_error", tenant=name)
            warnings.warn(
                f"promotions chain {path}: append failed ({e}) — the "
                f"{record.get('action', 'verdict')} itself is unaffected, "
                "but the audit ledger is missing this entry",
                stacklevel=3,
            )

    def _canary_reject(self, name: str, why: str, *, stage: str = "bits",
                       quality: dict | None = None, cause=None):
        """The ONE reject path every canary stage (load, bits, finiteness,
        quality) routes through: counter + flight record + chain verdict +
        warning + ``CanaryRejected`` (chained from ``cause`` when the
        reject wraps an underlying exception)."""
        obs_count("guard/canary_reject", tenant=name, stage=stage)
        flight.record("canary_reject", tenant=name, stage=stage, why=why)
        self._chain_verdict(name, action="reject", stage=stage, why=why,
                            **({"quality": quality} if quality else {}))
        warnings.warn(
            f"hot reload of tenant {name!r} REJECTED by the canary gate "
            f"({why}); the tenant keeps serving the previous bundle",
            stacklevel=3,
        )
        raise CanaryRejected(
            f"tenant {name!r}: {why}; serving is untouched") from cause

    def evaluate(self, tenant: str, date_idx: int, states, prices=None):
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(tenant, date_idx, states, prices).result()

    # -- introspection -------------------------------------------------------

    def tenant_source(self, name: str):
        """The tenant's CURRENT bundle source (directory path or in-memory
        policy) — what a control plane warm-starts a retrain from
        (``orp_tpu/pilot``). Tracks promotions: after ``reload_tenant``
        this is the promoted candidate's source."""
        with self._lock:
            if name not in self._tenants:
                raise KeyError(f"unknown tenant {name!r}; registered: "
                               f"{sorted(self._tenants)}")
            return self._tenants[name].source

    def stats(self) -> dict:
        """Per-tenant serving state: live/pending/activations plus the
        metrics summary of everything served so far."""
        with self._lock:
            # pending counters are _pending_lock state (the submit path
            # updates them without the host lock): snapshot them under
            # their own lock so a mid-increment read cannot tear.
            # Canonical order: _lock -> _pending_lock (ARCHITECTURE.md).
            with self._pending_lock:
                pending = {t.name: t.pending
                           for t in self._tenants.values()}
            return {
                t.name: {
                    "live": t.engine is not None,
                    "tier": self.tiers.tier_of(t.name),
                    "pending": pending[t.name],
                    "activations": t.activations,
                    "max_pending": t.max_pending,
                    "version": t.version,
                    **({"summary": t.metrics.summary()}
                       if t.metrics is not None else {}),
                    **({"drift": t.drift.scores()}
                       if t.drift is not None else {}),
                }
                for t in self._tenants.values()
            }

    def slo_report(self, default: SloPolicy | None = None) -> dict:
        """Per-tenant SLO burn rates off the registry latency histograms
        (``serve_request_latency_seconds{tenant=...}``). A tenant uses its
        own ``slo`` from ``add_tenant``, else ``default``; tenants with
        neither are skipped. ``burning`` flags rates > 1 — the budget is
        being consumed faster than it accrues."""
        out = {}
        with self._lock:
            tenants = list(self._tenants.values())
        for t in tenants:
            slo = t.slo if t.slo is not None else default
            if slo is None:
                continue
            hist = self.registry.histogram(LATENCY_HISTOGRAM,  # orp: noqa[ORP015] -- slo_report is an operator read path: this interns an EXISTING per-tenant series (a dict lookup), not hot-path churn
                                           {"tenant": t.name})
            rate = burn_rate(hist, slo)
            out[t.name] = {
                "latency_slo_ms": slo.latency_slo_ms,
                "error_budget": slo.error_budget,
                "violation_fraction": round(
                    hist.fraction_over(slo.latency_slo_ms / 1e3), 6),
                "burn_rate": round(rate, 4),
                "burning": rate > 1.0,
                # the same bounded window the fraction is computed over —
                # NOT the lifetime count (hist.count): the pair must
                # describe one window or violation estimates built from
                # them are fiction
                "window_requests": int(hist.snapshot().size),
                "lifetime_requests": int(hist.count),
            }
        return out

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Drain every live tenant's batcher and release all engines."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            batchers = [t.batcher for t in self._tenants.values()
                        if t.batcher is not None]
            for t in self._tenants.values():
                t.batcher = None
                t.engine = None
        for b in batchers:
            # outside the lock: the drain runs client done-callbacks
            b.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
