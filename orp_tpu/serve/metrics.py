"""Serving metrics: latency percentiles + throughput counters.

The standard inference-serving observables — per-request latency p50/p95/p99
and request/row throughput — kept host-side and allocation-light: cumulative
request/row counters plus a BOUNDED latency window (a deque of the most
recent ``window`` samples) behind one lock, so an always-on server records
forever without growing — percentiles are over the window, counts and
throughput over the whole lifetime. Recorded latencies must be
DEVICE-COMPLETE times: the engine blocks on the result before the caller's
clock stops, so these are end-to-end numbers, not dispatch times.
"""

from __future__ import annotations

import collections
import threading
import time

import numpy as np


class ServingMetrics:
    """Thread-safe latency/throughput recorder shared by engine callers and
    the micro-batcher worker. ``window`` bounds the retained latency samples
    (percentiles reflect the most recent that many requests)."""

    def __init__(self, *, window: int = 65536):
        if window < 1:
            raise ValueError(f"window={window} must be >= 1")
        self._window = int(window)
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._latencies_s: collections.deque[float] = collections.deque(
                maxlen=self._window)
            self._n_requests = 0
            self._rows = 0
            self._t_first: float | None = None
            self._t_last: float | None = None

    def record(self, latency_s: float, n_rows: int = 1) -> None:
        now = time.perf_counter()
        with self._lock:
            self._latencies_s.append(float(latency_s))
            self._n_requests += 1
            self._rows += int(n_rows)
            if self._t_first is None:
                self._t_first = now - latency_s  # window opens at first submit
            self._t_last = now

    @property
    def requests(self) -> int:
        with self._lock:
            return self._n_requests

    def summary(self) -> dict:
        """One flat dict: lifetime request/row counts and throughput, latency
        percentiles (ms) over the retained window. Zero-request summaries are
        all zeros (a bench that produced nothing should emit an honest
        record, not crash)."""
        with self._lock:
            lat = np.asarray(self._latencies_s, np.float64)
            n_requests = self._n_requests
            rows = self._rows
            elapsed = (
                (self._t_last - self._t_first)
                if self._t_first is not None else 0.0
            )
        if lat.size == 0:
            return {
                "requests": 0, "rows": 0, "elapsed_s": 0.0,
                "p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0,
                "mean_ms": 0.0, "max_ms": 0.0,
                "requests_per_s": 0.0, "rows_per_s": 0.0,
            }
        p50, p95, p99 = np.percentile(lat, [50, 95, 99])
        # a single instantaneous request has elapsed ~ its own latency;
        # guard the division anyway (perf_counter can tie at its resolution)
        denom = max(elapsed, 1e-9)
        return {
            "requests": int(n_requests),
            "rows": int(rows),
            "elapsed_s": round(elapsed, 6),
            "p50_ms": round(p50 * 1e3, 4),
            "p95_ms": round(p95 * 1e3, 4),
            "p99_ms": round(p99 * 1e3, 4),
            "mean_ms": round(float(lat.mean()) * 1e3, 4),
            "max_ms": round(float(lat.max()) * 1e3, 4),
            "requests_per_s": round(n_requests / denom, 2),
            "rows_per_s": round(rows / denom, 2),
        }
