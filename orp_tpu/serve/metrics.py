"""Serving metrics: latency percentiles + throughput counters.

Since the obs spine landed this is a thin FAÇADE over ``orp_tpu.obs``
registry instruments — a bounded ``Histogram`` for the latency window and
two ``Counter``s for lifetime request/row counts — so serving observables
live in the same exportable registry as every other framework metric
(Prometheus text / JSONL via ``obs/sink.py``). The external contract:
``record(latency_s, n_rows)`` with DEVICE-COMPLETE latencies (the engine
blocks on the result before the caller's clock stops), ``record_dispatch``
per coalesced device dispatch (occupancy / dispatches-per-request gauges —
the continuous batcher's amortisation observables), and ``summary()``
returning one flat dict whose pre-async keys keep their exact historical
rounding.

By default each instance owns a private registry (two concurrently
benched phases must not pollute each other's series); to publish into a
telemetry bundle instead, pass the ACTIVE SESSION's registry —
``registry=obs.state().registry`` — plus distinguishing ``labels``
(that registry is what ``obs.telemetry`` exports as ``metrics.prom``;
``serve/bench._phase_metrics`` is the worked example).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from orp_tpu.obs.registry import Registry

LATENCY_HISTOGRAM = "serve_request_latency_seconds"
REQUESTS_COUNTER = "serve_requests_total"
ROWS_COUNTER = "serve_rows_total"
DISPATCHES_COUNTER = "serve_dispatches_total"
OCCUPANCY_GAUGE = "serve_batch_occupancy"
DISPATCHES_PER_REQUEST_GAUGE = "serve_dispatches_per_request"


class ServingMetrics:
    """Thread-safe latency/throughput recorder shared by engine callers and
    the micro-batcher worker. ``window`` bounds the retained latency samples
    (percentiles reflect the most recent that many requests)."""

    def __init__(self, *, window: int = 65536,
                 registry: Registry | None = None,
                 labels: dict[str, str] | None = None):
        if window < 1:
            raise ValueError(f"window={window} must be >= 1")
        self._window = int(window)
        self.registry = registry if registry is not None else Registry()
        self._hist = self.registry.histogram(
            LATENCY_HISTOGRAM, labels, window=self._window)
        self._requests = self.registry.counter(REQUESTS_COUNTER, labels)
        self._rows = self.registry.counter(ROWS_COUNTER, labels)
        # dispatch-amortisation observables (the "26 dispatches for 256
        # requests" pathology as first-class numbers): how many device
        # dispatches the recorded traffic cost, the fraction of each
        # dispatched bucket that carried real rows, and the running
        # dispatches-per-request ratio (1.0 = no coalescing at all;
        # the continuous batcher should hold it well under 0.1 on bursts)
        self._dispatches = self.registry.counter(DISPATCHES_COUNTER, labels)
        self._occupancy = self.registry.gauge(OCCUPANCY_GAUGE, labels)
        self._dpr = self.registry.gauge(DISPATCHES_PER_REQUEST_GAUGE, labels)
        self._dispatch_rows = 0
        self._dispatch_capacity = 0
        # façade lock: record()/summary() take it around ALL their instrument
        # touches, preserving the original one-lock atomicity (a concurrent
        # summary never sees requests=N+1 with N window samples). The
        # instruments' own locks nest inside — ordering is always façade ->
        # instrument, so no deadlock.
        self._lock = threading.Lock()
        # fresh instruments start at zero, so construction does NOT reset:
        # a second façade over the same shared-registry series ACCUMULATES
        # into it (the counter-natural semantics) instead of silently wiping
        # what the first one recorded. reset() stays for explicit wipes.
        self._t_first: float | None = None
        self._t_last: float | None = None

    def reset(self) -> None:
        with self._lock:
            self._hist.reset()
            self._requests.reset()
            self._rows.reset()
            self._dispatches.reset()
            self._occupancy.set(0.0)
            self._dpr.set(0.0)
            self._dispatch_rows = 0
            self._dispatch_capacity = 0
            self._t_first = None
            self._t_last = None

    def record(self, latency_s: float, n_rows: int = 1) -> None:
        now = time.perf_counter()
        with self._lock:
            self._record_locked(now, latency_s, n_rows)

    def record_many(self, samples) -> None:
        """Bulk-record ``(latency_s, n_rows)`` pairs under ONE lock pass per
        instrument — the continuous batcher resolves a whole coalesced
        batch at once, and per-request lock churn would put the recorder in
        the hot path it is measuring."""
        if not samples:
            return
        now = time.perf_counter()
        with self._lock:
            self._hist.observe_many(lat for lat, _ in samples)
            self._requests.inc(len(samples))
            self._rows.inc(sum(n for _, n in samples))
            if self._t_first is None:
                self._t_first = now - samples[0][0]
            self._t_last = now
            d = self._dispatches.value
            if d:
                self._dpr.set(d / self._requests.value)

    def _record_locked(self, now: float, latency_s: float, n_rows: int) -> None:
        self._hist.observe(float(latency_s))
        self._requests.inc()
        self._rows.inc(int(n_rows))
        if self._t_first is None:
            self._t_first = now - latency_s  # window opens at first submit
        self._t_last = now
        d = self._dispatches.value
        if d:
            self._dpr.set(d / self._requests.value)

    def record_dispatch(self, n_requests: int, n_rows: int,
                        capacity: int | None = None) -> None:
        """One device dispatch carrying ``n_requests`` coalesced requests of
        ``n_rows`` total rows into a bucket of ``capacity`` rows (the padded
        executable shape). Updates the dispatch counter and the occupancy /
        dispatches-per-request gauges."""
        with self._lock:
            self._dispatches.inc()
            if capacity:
                self._dispatch_rows += int(n_rows)
                self._dispatch_capacity += int(capacity)
                self._occupancy.set(
                    self._dispatch_rows / self._dispatch_capacity)
            reqs = self._requests.value
            if reqs:
                self._dpr.set(self._dispatches.value / reqs)

    @property
    def requests(self) -> int:
        with self._lock:
            return self._requests.value

    def summary(self) -> dict:
        """One flat dict: lifetime request/row counts and throughput, latency
        percentiles (ms) over the retained window. Zero-request summaries are
        all zeros (a bench that produced nothing should emit an honest
        record, not crash)."""
        with self._lock:
            lat = self._hist.snapshot()
            n_requests = self._requests.value
            rows = self._rows.value
            dispatches = self._dispatches.value
            occupancy = (self._dispatch_rows / self._dispatch_capacity
                         if self._dispatch_capacity else 0.0)
            elapsed = (
                (self._t_last - self._t_first)
                if self._t_first is not None else 0.0
            )
        if lat.size == 0:
            return {
                "requests": 0, "rows": 0, "elapsed_s": 0.0,
                "p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0,
                "mean_ms": 0.0, "max_ms": 0.0,
                "requests_per_s": 0.0, "rows_per_s": 0.0,
                "dispatches": int(dispatches),
                "dispatches_per_request": 0.0,
                "batch_occupancy": round(occupancy, 4),
            }
        p50, p95, p99 = np.percentile(lat, [50, 95, 99])
        # a single instantaneous request has elapsed ~ its own latency;
        # guard the division anyway (perf_counter can tie at its resolution)
        denom = max(elapsed, 1e-9)
        return {
            "requests": int(n_requests),
            "rows": int(rows),
            "elapsed_s": round(elapsed, 6),
            "p50_ms": round(p50 * 1e3, 4),
            "p95_ms": round(p95 * 1e3, 4),
            "p99_ms": round(p99 * 1e3, 4),
            "mean_ms": round(float(lat.mean()) * 1e3, 4),
            "max_ms": round(float(lat.max()) * 1e3, 4),
            "requests_per_s": round(n_requests / denom, 2),
            "rows_per_s": round(rows / denom, 2),
            # dispatch amortisation: how many device dispatches the traffic
            # cost, the filled fraction of each dispatched bucket, and
            # dispatches/request (1.0 = no coalescing)
            "dispatches": int(dispatches),
            "dispatches_per_request": round(dispatches / n_requests, 4),
            "batch_occupancy": round(occupancy, 4),
        }
