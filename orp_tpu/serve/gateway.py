"""The socket gateway: a non-Python-per-row ingest front over ``ServeHost``.

The serve tier's last serialization point (ROADMAP, PR 7's measurement) was
the per-request Python submit path — ~6µs of object churn per request no
matter how well the device was amortized. This module is the other half of
the columnar fix: requests arrive over TCP as ``orp-ingest-v1`` frames
(``serve/wire.py``), and the ENTIRE per-frame Python bill is

    decode (header check + 3 buffer views)
    → ``ServeHost.submit_block`` (one lock pass, one future)
    → encode (status/phi/psi/value ``tobytes``)

amortized over every row in the block. A 1024-row frame costs the gateway
the same Python as a 1-row frame.

Transport: length-prefixed frames — a ``<u4`` byte count, then the frame —
over a plain TCP stream; one handler thread per connection (the GIL is not
the bottleneck: handlers spend their time parked on ``recv`` or on the
block future, both of which release it). Malformed frames are answered
with a structured ERROR frame in flag-speak; the framing itself (length
prefix) stays intact, so one bad frame never poisons the connection.
``close()`` drains gracefully: stop accepting, let every handler finish
the frame it is serving, then shut the sockets.

``GatewayClient`` is the reference client (the README's 5-line snippet,
the loopback bench, the doctor probe): connect, ``submit_block``, read the
columnar reply.
"""

from __future__ import annotations

import socket
import struct
import threading

import numpy as np

from orp_tpu.obs import count as obs_count
from orp_tpu.serve import wire
from orp_tpu.serve.ingest import BlockResult

_LEN = struct.Struct("<I")
#: transport-level ceiling on one frame (the wire's own MAX_ROWS is the
#: semantic cap; this one bounds the recv allocation before decoding)
MAX_FRAME_BYTES = 1 << 28


class GatewayError(RuntimeError):
    """The server answered with a structured ERROR frame; the message is
    the server's flag-speak refusal."""


def _recv_exact(sock: socket.socket, n: int, closed) -> bytes | None:
    """Read exactly ``n`` bytes, polling the drain flag between timeouts;
    None when the peer closed (or the gateway is draining)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        if closed is not None and closed.is_set():
            return None
        try:
            k = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            if closed is None:
                raise  # a client with no drain flag wants its timeout
            continue
        except OSError:
            return None
        if k == 0:
            return None
        got += k
    return bytes(buf)


def _send_frame(sock: socket.socket, frame: bytes) -> None:
    sock.sendall(_LEN.pack(len(frame)) + frame)


def _recv_frame(sock: socket.socket, closed=None,
                max_bytes: int = MAX_FRAME_BYTES) -> bytes | None:
    head = _recv_exact(sock, _LEN.size, closed)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > max_bytes:
        raise wire.WireError(
            f"frame length {n} exceeds the {max_bytes}-byte transport cap "
            "— split the block")
    return _recv_exact(sock, n, closed)


class ServeGateway:
    """Length-prefixed TCP front over a :class:`~orp_tpu.serve.host.ServeHost`.

    ``host``           — the multi-tenant host that serves decoded blocks.
    ``addr``/``port``  — bind address (``port=0`` picks a free port; read
    it back from :attr:`address`).
    ``default_tenant`` — tenant for frames whose tenant field is empty.
    ``reply_timeout_s`` — bound on waiting for a block's future (a stuck
    block answers the CONNECTION with an ERROR frame instead of wedging
    the handler forever).

    Per-connection observability: ``serve/gateway_connections`` (opened),
    ``serve/gateway_frames{kind}``, ``serve/gateway_rows``,
    ``serve/gateway_errors{stage}`` counters, plus :meth:`stats` for the
    live per-connection frame/row ledgers.
    """

    def __init__(self, host, *, addr: str = "127.0.0.1", port: int = 0,
                 default_tenant: str | None = None, backlog: int = 16,
                 reply_timeout_s: float = 60.0,
                 max_frame_bytes: int = MAX_FRAME_BYTES):
        self.host = host
        self.default_tenant = default_tenant
        self.reply_timeout_s = float(reply_timeout_s)
        self.max_frame_bytes = int(max_frame_bytes)
        self._closed = threading.Event()
        self._lock = threading.Lock()
        self._conns: dict[int, dict] = {}
        self._handlers: list[threading.Thread] = []
        self._next_conn = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((addr, int(port)))
        self._sock.listen(backlog)
        self.address: tuple[str, int] = self._sock.getsockname()[:2]
        self._acceptor = threading.Thread(
            target=self._accept_loop, name="orp-serve-gateway", daemon=True)
        self._acceptor.start()

    # -- accept / serve ------------------------------------------------------

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.25)
        while not self._closed.is_set():
            try:
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: the drain path
            conn.settimeout(0.25)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                cid = self._next_conn
                self._next_conn += 1
                self._conns[cid] = {"peer": f"{peer[0]}:{peer[1]}",
                                    "frames": 0, "rows": 0, "errors": 0}
                t = threading.Thread(
                    target=self._serve_conn, args=(conn, cid),
                    name=f"orp-gateway-conn-{cid}", daemon=True)
                # prune finished handlers so a long-lived gateway's ledger
                # stays O(live connections)
                self._handlers = [h for h in self._handlers if h.is_alive()]
                self._handlers.append(t)
            obs_count("serve/gateway_connections")
            t.start()

    def _serve_conn(self, conn: socket.socket, cid: int) -> None:
        stats = self._conns[cid]
        try:
            while not self._closed.is_set():
                try:
                    frame = _recv_frame(conn, self._closed,
                                        self.max_frame_bytes)
                except wire.WireError as e:
                    # transport-level refusal: answer, then close — past an
                    # oversized length prefix the stream offset is garbage
                    stats["errors"] += 1
                    obs_count("serve/gateway_errors", stage="transport")
                    self._try_send(conn, wire.encode_error(str(e)))
                    return
                if frame is None:
                    return  # peer closed (or drain): a clean end
                stats["frames"] += 1
                reply = self._handle_frame(frame, stats)
                if not self._try_send(conn, reply):
                    return
        finally:
            try:
                conn.close()
            except OSError:  # orp: noqa[ORP009] -- best-effort close of a dead socket; nothing to emit
                pass
            with self._lock:
                self._conns.pop(cid, None)

    def _handle_frame(self, frame: bytes, stats: dict) -> bytes:
        """decode → submit_block → encode: the whole per-frame Python bill.
        Every failure mode becomes a structured ERROR frame in flag-speak;
        the connection survives anything the framing survived."""
        try:
            kind = wire.decode_kind(frame)
        except wire.WireError as e:
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="decode")
            return wire.encode_error(str(e))
        obs_count("serve/gateway_frames", kind=str(kind), sink_event=False)
        if kind == wire.KIND_PING:
            return wire.encode_pong()
        if kind != wire.KIND_REQUEST:
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="decode")
            return wire.encode_error(
                "this endpoint takes request/ping frames only")
        try:
            req = wire.decode_request(frame)
        except wire.WireError as e:
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="decode")
            return wire.encode_error(str(e))
        tenant = req["tenant"] or self.default_tenant
        if tenant is None:
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="route")
            return wire.encode_error(
                "frame names no tenant and the gateway has no default — "
                "set the tenant field or start with --tenant")
        try:
            fut = self.host.submit_block(tenant, req["date_idx"],
                                         req["states"], req["prices"],
                                         req["deadlines"])
            result: BlockResult = fut.result(timeout=self.reply_timeout_s)
        except Exception as e:  # orp: noqa[ORP009] -- emitted: counted AND shipped to the client as an ERROR frame
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="serve")
            return wire.encode_error(f"{type(e).__name__}: {e}")
        n = result.n_rows
        stats["rows"] += n
        obs_count("serve/gateway_rows", n, sink_event=False)
        return wire.encode_reply(result, date_idx=req["date_idx"])

    def _try_send(self, conn: socket.socket, frame: bytes) -> bool:
        try:
            _send_frame(conn, frame)
            return True
        except OSError:
            obs_count("serve/gateway_errors", stage="send")
            return False

    # -- introspection / lifecycle -------------------------------------------

    def stats(self) -> dict:
        """Live per-connection ledgers: ``{conn_id: {peer, frames, rows,
        errors}}``."""
        with self._lock:
            return {cid: dict(s) for cid, s in self._conns.items()}

    def close(self, timeout: float = 5.0) -> None:
        """Graceful drain: stop accepting, let every handler finish the
        frame it is serving (their recv polls notice the flag), then close
        the listener."""
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._sock.close()
        except OSError:  # orp: noqa[ORP009] -- already closed; the drain continues
            pass
        self._acceptor.join(timeout)
        with self._lock:
            handlers = list(self._handlers)
        for t in handlers:
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class GatewayClient:
    """The reference ``orp-ingest-v1`` client: one TCP connection, columnar
    frames in, :class:`BlockResult` out. The five-line usage::

        from orp_tpu.serve.gateway import GatewayClient
        with GatewayClient("127.0.0.1", 7433) as c:
            res = c.submit_block("desk-a", date_idx=3, states=feats)
        print(res.phi, res.status)
    """

    def __init__(self, addr: str, port: int, *, timeout_s: float = 60.0):
        self._sock = socket.create_connection((addr, int(port)),
                                              timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()  # one in-flight frame per connection

    def submit_block(self, tenant: str, date_idx: int, states, prices=None,
                     deadlines=None, *,
                     deadline_ms: float | None = None) -> BlockResult:
        """Ship one block and block on its columnar reply. Raises
        :class:`GatewayError` with the server's flag-speak message when the
        server refused the frame (or the serve itself failed)."""
        frame = wire.encode_request(tenant, date_idx, states, prices,
                                    deadlines, deadline_ms=deadline_ms)
        reply = self._roundtrip(frame)
        if wire.decode_kind(reply) == wire.KIND_ERROR:
            raise GatewayError(wire.decode_error(reply))
        return wire.decode_reply(reply)

    def ping(self) -> bool:
        """One PING round trip — the doctor probe's liveness check."""
        reply = self._roundtrip(wire.encode_ping())
        return wire.decode_kind(reply) == wire.KIND_PONG

    def _roundtrip(self, frame: bytes) -> bytes:
        with self._lock:
            _send_frame(self._sock, frame)
            reply = _recv_frame(self._sock)
        if reply is None:
            raise GatewayError("connection closed by the gateway mid-reply")
        return reply

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:  # orp: noqa[ORP009] -- best-effort close; nothing to emit
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
