"""The socket gateway: a delivery-guaranteed ingest front over ``ServeHost``.

The serve tier's last serialization point (ROADMAP, PR 7's measurement) was
the per-request Python submit path — ~6µs of object churn per request no
matter how well the device was amortized. This module is the other half of
the columnar fix: requests arrive over TCP as ``orp-ingest`` frames
(``serve/wire.py``), and the ENTIRE per-frame Python bill is

    decode (header check + 3 buffer views)
    → ``ServeHost.submit_block`` (one lock pass, one future)
    → encode (status/phi/psi/value ``tobytes``)

amortized over every row in the block. A 1024-row frame costs the gateway
the same Python as a 1-row frame.

**Delivery guarantees (orp-ingest-v2).** Every robustness feature below the
process boundary (guard's deadlines, shedding, device-loss replay) used to
stop at the socket: a dropped connection, a stalled mid-frame client or a
gateway restart silently lost in-flight rows with no way for the producer
to know which. The v2 protocol closes that gap:

- **sessions** — a HELLO/RESUME handshake binds a connection to a session
  token; sequenced REQUEST frames (monotonically increasing per-session
  ``seq``) are deduplicated against the session's admitted window, so a
  reconnecting producer replaying unacknowledged frames gets
  at-least-once-SUBMIT / exactly-once-SERVE semantics: a frame already
  answered is re-answered from a bounded **reply cache**, a frame still in
  flight is adopted (its reply lands on the new connection), and only a
  genuinely new frame reaches the batcher.
- **frame deadline** — a peer holding a HALF-WRITTEN frame past
  ``frame_deadline_s`` is answered with an ERROR frame and reset
  (``serve/gateway_errors{stage="stall"}``), freeing the handler; other
  connections' frames keep serving throughout (one handler thread per
  connection).
- **backpressure** — past ``max_inflight_replies`` unanswered frames on
  one connection, the next frame is refused with a structured BUSY frame
  (the producer is told to slow down and resend; distinct from watermark
  shed, where rows died by policy).
- **drain-and-redirect** — ``close(successor=(host, port))`` answers NEW
  frames with a REDIRECT frame naming the successor while in-flight frames
  finish, so two gateway processes hand off a live producer with zero lost
  rows.

``GatewayClient`` is the minimal v1 reference client (one frame in flight,
no replay); ``serve/client.py::ResilientGatewayClient`` is the v2 producer
that turns these primitives into reconnect-replay delivery.
"""

from __future__ import annotations

import collections
import secrets
import socket
import struct
import threading
import time

from orp_tpu.guard import inject
from orp_tpu.obs import count as obs_count
from orp_tpu.obs import emit_trace_span, flight, prometheus_text
from orp_tpu.obs import state as obs_state
from orp_tpu.serve import wire
from orp_tpu.serve.batcher import SlimFuture
from orp_tpu.serve.ingest import BlockResult

_LEN = struct.Struct("<I")
#: transport-level ceiling on one frame (the wire's own MAX_ROWS is the
#: semantic cap; this one bounds the recv allocation before decoding)
MAX_FRAME_BYTES = 1 << 28


class GatewayError(RuntimeError):
    """The server answered with a structured ERROR frame; the message is
    the server's flag-speak refusal."""


class FrameStall(wire.WireError):
    """A partial frame outlived the read deadline: the peer wrote some
    bytes and went silent. The connection is reset — the stream offset is
    unknowable — and a sequenced producer replays the frame on reconnect."""


def _recv_exact(sock: socket.socket, n: int, closed, clock=None,
                idle=None) -> bytes | None:
    """Read exactly ``n`` bytes, polling the drain flag between timeouts;
    None when the peer closed (or the gateway is draining).

    ``clock`` (``{"t0": float|None, "wall": float|None}``, shared across
    one frame's reads): ``t0`` is stamped at the frame's first byte and a
    partial read outliving ``wall`` seconds raises :class:`FrameStall` —
    the unbounded-poll hole ORP014 exists to keep closed. ``idle`` is
    called on timeouts while NO frame is in progress (client-side
    housekeeping between replies)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        if closed is not None and closed.is_set():
            return None
        if (clock is not None and clock["t0"] is not None
                and clock["wall"] is not None
                and time.perf_counter() - clock["t0"] > clock["wall"]):
            raise FrameStall(  # orp: noqa[ORP016] -- the catcher emits: the handler's stall eviction counts serve/gateway_errors{stage=stall} + the flight record with the stall wall
                f"partial frame stalled past the {clock['wall'] * 1e3:.0f}ms "
                "frame deadline — resetting the connection (a sequenced "
                "client replays the frame on reconnect)")
        try:
            k = sock.recv_into(view[got:], n - got)  # orp: noqa[ORP014] -- the socket's poll timeout is set at accept/connect; `clock` bounds a partial frame
        except socket.timeout:
            if closed is None and clock is None and idle is None:
                raise  # a caller with no polling contract wants its timeout
            if idle is not None and (clock is None or clock["t0"] is None):
                idle()
            continue
        except OSError:
            return None
        if k == 0:
            return None
        got += k
        if clock is not None and clock["t0"] is None:
            clock["t0"] = time.perf_counter()
    return bytes(buf)


def _send_frame(sock: socket.socket, frame: bytes) -> None:
    sock.sendall(_LEN.pack(len(frame)) + frame)  # orp: noqa[ORP014] -- every socket entering this helper had settimeout applied at accept/connect


def _recv_frame(sock: socket.socket, closed=None,
                max_bytes: int = MAX_FRAME_BYTES, *,
                deadline_s: float | None = None,
                idle=None) -> bytes | None:
    """One length-prefixed frame off the stream. ``deadline_s`` starts at
    the frame's FIRST byte (length prefix included): a peer that begins a
    frame must finish it inside the deadline or the read raises
    :class:`FrameStall`. An idle connection (no bytes at all) waits
    forever — silence between frames is a healthy producer."""
    clock = (None if deadline_s is None and idle is None
             else {"t0": None, "wall": deadline_s})
    head = _recv_exact(sock, _LEN.size, closed, clock=clock, idle=idle)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > max_bytes:
        raise wire.WireError(
            f"frame length {n} exceeds the {max_bytes}-byte transport cap "
            "— split the block")
    return _recv_exact(sock, n, closed, clock=clock)


def _chain(relay: SlimFuture, fut) -> None:
    """Copy a resolved block future into the session's relay future (the
    adoptable pending entry installed at claim time)."""
    err = fut.exception()
    if relay.set_running_or_notify_cancel():
        if err is not None:
            relay.set_exception(err)
        else:
            relay.set_result(fut.result())


class _Session:
    """One producer's delivery window, independent of any connection: the
    highest admitted seq, the in-flight futures, and the bounded cache of
    encoded replies that answers replayed duplicates without re-dispatch."""

    __slots__ = ("token", "lock", "last_seq", "pending", "replies",
                 "evicted_below", "rows", "frames", "replayed_from_cache")

    def __init__(self, token: bytes):
        self.token = token
        self.lock = threading.Lock()
        self.last_seq = 0                        # highest ADMITTED seq
        self.pending: dict[int, tuple] = {}      # seq -> (future, date_idx)
        self.replies: collections.OrderedDict[int, bytes] = \
            collections.OrderedDict()            # seq -> encoded reply frame
        # seqs below this left the reply cache: the one frame class the
        # window can no longer answer (a frame BELOW it that is neither
        # cached nor pending was served and forgotten)
        self.evicted_below = 1
        self.rows = 0
        self.frames = 0
        self.replayed_from_cache = 0


class _Conn:
    """Per-connection handler state: the socket, its send lock, the bound
    session, the in-flight reply count the BUSY bound acts on, and the
    reply outbox its lazy writer thread drains (block replies must never
    be sent from the batcher's resolving thread — a consumer that stops
    reading would stall the dispatch loop for every tenant)."""

    __slots__ = ("sock", "send_lock", "lock", "session", "inflight", "stats",
                 "outbox", "cv", "writer", "dead")

    def __init__(self, sock, stats):
        self.sock = sock
        self.send_lock = threading.Lock()
        self.lock = threading.Lock()
        self.session: _Session | None = None
        self.inflight = 0
        self.stats = stats
        self.outbox: collections.deque[bytes] = collections.deque()
        self.cv = threading.Condition()
        self.writer: threading.Thread | None = None
        self.dead = False


class ServeGateway:
    """Length-prefixed TCP front over a :class:`~orp_tpu.serve.host.ServeHost`.

    ``host``           — the multi-tenant host that serves decoded blocks.
    ``addr``/``port``  — bind address (``port=0`` picks a free port; read
    it back from :attr:`address`).
    ``default_tenant`` — tenant for frames whose tenant field is empty.
    ``reply_timeout_s`` — bound on waiting for a v1 block's future (a stuck
    block answers the CONNECTION with an ERROR frame instead of wedging
    the handler forever).
    ``frame_deadline_s`` — partial-frame read deadline: a peer that began
    a frame and stalls past it is answered with an ERROR frame and reset.
    ``max_inflight_replies`` — per-connection unanswered-frame bound; past
    it sequenced frames are refused with BUSY (backpressure, not shed).
    ``reply_cache``    — per-session encoded-reply window answering
    replayed duplicates (size it ≥ the producer's replay window).

    Per-connection observability: ``serve/gateway_connections`` (opened),
    ``serve/gateway_frames{kind}``, ``serve/gateway_rows``,
    ``serve/gateway_errors{stage}``, ``serve/gateway_busy``,
    ``serve/gateway_redirects``, ``serve/gateway_replays`` counters, plus
    :meth:`stats` (live per-connection ledgers) and :meth:`totals` (the
    cumulative ledger, retired connections included — two draining
    gateways' ``totals()["rows"]`` sum to the rows the fleet served).

    The telemetry plane (PR 12): METRICS/HEALTH wire kinds answer the LIVE
    Prometheus exposition (:meth:`metrics_text`) and the JSON health
    document (:meth:`health_report` — which also dumps the armed flight
    recorder, the doctor hook); trace-stamped frames (``FLAG_TRACE``)
    leave decode/encode segment spans here and queue/dispatch/resolve
    spans in the batcher, all under the producer's trace id, with the
    compact server-timing block returned in the reply's trace extension.
    """

    def __init__(self, host, *, addr: str = "127.0.0.1", port: int = 0,
                 default_tenant: str | None = None, backlog: int = 16,
                 reply_timeout_s: float = 60.0,
                 frame_deadline_s: float | None = 30.0,
                 max_inflight_replies: int = 8,
                 reply_cache: int = 64,
                 max_sessions: int = 256,
                 max_frame_bytes: int = MAX_FRAME_BYTES):
        self.host = host
        self.default_tenant = default_tenant
        self.reply_timeout_s = float(reply_timeout_s)
        self.frame_deadline_s = (None if frame_deadline_s is None
                                 else float(frame_deadline_s))
        self.max_inflight_replies = int(max_inflight_replies)
        self.reply_cache = int(reply_cache)
        self.max_sessions = int(max_sessions)
        self.max_frame_bytes = int(max_frame_bytes)
        self._closed = threading.Event()
        self._draining = threading.Event()
        self.aborted = threading.Event()
        self._redirect: tuple[str, int] | None = None
        self._lock = threading.Lock()
        self._conns: dict[int, dict] = {}
        self._csocks: dict[int, socket.socket] = {}
        self._handlers: list[threading.Thread] = []
        self._next_conn = 0
        self._sessions: collections.OrderedDict[bytes, _Session] = \
            collections.OrderedDict()
        self._retired = {"frames": 0, "rows": 0, "errors": 0}
        # retired connections keep their LIVE stats dicts for a while: a
        # frame admitted on a connection that then died settles its row
        # count from the resolve callback AFTER the handler retired — a
        # snapshot-at-retire would lose those rows from totals() (the
        # fleet-handoff row-sum contract). Folded into _retired only once
        # old enough that every callback has long settled.
        self._recent_retired: collections.deque = collections.deque()
        self._submitted_frames = 0
        # replies mid-callback (pending already deleted, send not yet done):
        # the drain must wait these out too, or close() can cut a reply off
        # between the pending-delete and its send
        self._replying = 0
        # poll fine enough that a stall is caught soon after its deadline
        self._poll_s = (0.25 if self.frame_deadline_s is None
                        else min(0.25, max(0.005, self.frame_deadline_s / 5)))
        # pre-intern the core serve series into the host registry so a
        # LIVE scrape (METRICS wire kind / --metrics-port) always carries
        # them — a fresh gateway's exposition must be probe-able
        # (`orp doctor --metrics`) before the first frame arrives
        reg = host.registry
        reg.counter("serve/gateway_rows")
        reg.counter("guard/shed")
        # labelled like the batcher's real observations (obs_observe with
        # outcome="served") — an unlabeled twin would shadow the live
        # series in label-free quantile lookups (`orp top`)
        reg.histogram("serve/queue_age_seconds", {"outcome": "served"})
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((addr, int(port)))
        self._sock.listen(backlog)
        self.address: tuple[str, int] = self._sock.getsockname()[:2]
        self._acceptor = threading.Thread(
            target=self._accept_loop, name="orp-serve-gateway", daemon=True)
        self._acceptor.start()

    # -- accept / serve ------------------------------------------------------

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.25)
        while not self._closed.is_set() and not self._draining.is_set():
            try:
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us: the drain path
            conn.settimeout(self._poll_s)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                cid = self._next_conn
                self._next_conn += 1
                self._conns[cid] = {"peer": f"{peer[0]}:{peer[1]}",
                                    "frames": 0, "rows": 0, "errors": 0}
                self._csocks[cid] = conn
                t = threading.Thread(
                    target=self._serve_conn, args=(conn, cid),
                    name=f"orp-gateway-conn-{cid}", daemon=True)
                # prune finished handlers so a long-lived gateway's ledger
                # stays O(live connections)
                self._handlers = [h for h in self._handlers if h.is_alive()]
                self._handlers.append(t)
            obs_count("serve/gateway_connections")
            t.start()

    def _serve_conn(self, conn: socket.socket, cid: int) -> None:
        # the accept loop registers cid under _lock before starting this
        # thread; take the same lock for the lookup so the read is ordered
        # against concurrent registrations mutating the dict
        with self._lock:
            stats = self._conns[cid]
        st = _Conn(conn, stats)
        try:
            while not self._closed.is_set():
                try:
                    frame = _recv_frame(conn, self._closed,
                                        self.max_frame_bytes,
                                        deadline_s=self.frame_deadline_s)
                except FrameStall as e:
                    # the stalled-reader eviction: answer, reset, free the
                    # handler — the stream offset is garbage past the tear
                    stats["errors"] += 1
                    obs_count("serve/gateway_errors", stage="stall")
                    flight.record("wire_error", stage="stall",
                                  peer=stats.get("peer"))
                    self._send_on(st, wire.encode_error(str(e)))
                    return
                except wire.WireError as e:
                    # transport-level refusal: answer, then close — past an
                    # oversized length prefix the stream offset is garbage
                    stats["errors"] += 1
                    obs_count("serve/gateway_errors", stage="transport")
                    flight.record("wire_error", stage="transport",
                                  peer=stats.get("peer"))
                    self._send_on(st, wire.encode_error(str(e)))
                    return
                if frame is None:
                    return  # peer closed (or drain): a clean end
                stats["frames"] += 1
                if not self._handle_frame(frame, st):
                    return
        finally:
            with st.cv:
                st.dead = True
                st.cv.notify_all()  # release the writer thread
            try:
                conn.close()
            except OSError:  # orp: noqa[ORP009] -- best-effort close of a dead socket; nothing to emit
                pass
            with self._lock:
                gone = self._conns.pop(cid, None)
                self._csocks.pop(cid, None)
                if gone is not None:
                    # keep the dict LIVE (late resolve callbacks still
                    # write rows into it); fold only well-settled ones
                    self._recent_retired.append(gone)
                    while len(self._recent_retired) > 1024:
                        old = self._recent_retired.popleft()
                        for k in ("frames", "rows", "errors"):
                            self._retired[k] += old[k]

    # -- frame handling ------------------------------------------------------

    def _handle_frame(self, frame: bytes, st: _Conn) -> bool:
        """One frame, any protocol version. Returns False when the
        connection must close (injected kill, reset-after-submit). Every
        per-frame failure mode becomes a structured ERROR frame in
        flag-speak; the connection survives anything the framing
        survived."""
        stats = st.stats
        try:
            kind, seq = wire.frame_meta(frame)
        except wire.WireError as e:
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="decode")
            self._send_on(st, wire.encode_error(str(e)))
            # a handshaken stream that yields an undecodable header is
            # desynced — reset it so the producer reconnects and replays
            # (the client treats a seq-less ERROR as connection poison)
            return st.session is None
        obs_count("serve/gateway_frames", kind=str(kind), sink_event=False)
        if kind == wire.KIND_PING:
            return self._send_on(st, wire.encode_pong())
        if kind == wire.KIND_METRICS:
            # the live scrape — answered even mid-drain: a draining
            # gateway's telemetry is exactly what an operator watches
            return self._send_on(st, wire.encode_metrics(
                self.metrics_text()))
        if kind == wire.KIND_HEALTH:
            try:
                ask = wire.decode_health(frame)
            except wire.WireError as e:
                st.stats["errors"] += 1
                obs_count("serve/gateway_errors", stage="decode")
                return self._send_on(st, wire.encode_error(str(e)))
            return self._send_on(st, wire.encode_health(self.health_report(
                dump_flight=bool(ask.get("dump_flight")),
                route=ask.get("route"))))
        if kind == wire.KIND_HELLO:
            return self._handle_hello(frame, st)
        if kind != wire.KIND_REQUEST:
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="decode")
            return self._send_on(st, wire.encode_error(
                "this endpoint takes request/ping/hello frames only",
                seq=seq or None))
        if self._draining.is_set():
            # drain-and-redirect: NEW frames go elsewhere, in-flight ones
            # finish and their replies flush — zero rows lost in the
            # handoff. REDIRECT is a v2-only kind: an unsequenced (v1)
            # producer gets the draining ERROR its decoder understands
            if self._redirect is not None and seq:
                obs_count("serve/gateway_redirects")
                return self._send_on(st, wire.encode_redirect(
                    *self._redirect, seq=seq))
            msg = ("gateway is draining — reconnect elsewhere and replay"
                   if self._redirect is None else
                   "gateway is draining — reconnect to "
                   f"{self._redirect[0]}:{self._redirect[1]}")
            return self._send_on(st, wire.encode_error(msg, seq=seq or None))
        if seq:
            return self._handle_request_v2(frame, seq, st)
        return self._handle_request_v1(frame, st)

    def _handle_hello(self, frame: bytes, st: _Conn) -> bool:
        try:
            token = wire.decode_hello(frame)
        except wire.WireError as e:
            st.stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="decode")
            return self._send_on(st, wire.encode_error(str(e)))
        if self._draining.is_set() and self._redirect is not None:
            obs_count("serve/gateway_redirects")
            return self._send_on(st, wire.encode_redirect(*self._redirect))
        with self._lock:
            sess = self._sessions.get(token) if token else None
            if sess is None:
                # adopt an unknown token verbatim (a successor gateway has
                # no state for a resumed session: the producer replays every
                # unacked frame and last_seq=0 admits them all)
                sess = _Session(token or secrets.token_hex(8).encode())
                self._sessions[sess.token] = sess
                while len(self._sessions) > self.max_sessions:
                    # prefer evicting a session with nothing in flight —
                    # killing one mid-frame silently voids its replay
                    # guarantee (racy len() read: a heuristic, not a gate)
                    victim = next(
                        (t for t, s in self._sessions.items()
                         if not s.pending and s is not sess), None)
                    if victim is None:
                        victim = next(t for t in self._sessions
                                      if t != sess.token)
                    del self._sessions[victim]
            else:
                self._sessions.move_to_end(token)
        st.session = sess
        return self._send_on(st, wire.encode_welcome(sess.token,
                                                     sess.last_seq))

    def _handle_request_v2(self, frame: bytes, seq: int, st: _Conn) -> bool:
        sess = st.session
        if sess is None:
            st.stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="route")
            return self._send_on(st, wire.encode_error(
                "sequenced frames need a HELLO handshake first — send HELLO "
                "(empty token) and use the WELCOME token to resume",
                seq=seq))
        # decode BEFORE the window check: a fresh frame must be CLAIMED
        # (pending entry installed) inside the same lock hold that
        # classified it, and the claim needs the decoded date
        t0 = time.perf_counter()
        try:
            req = wire.decode_request(frame)
        except wire.WireError as e:
            st.stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="decode")
            flight.record("wire_error", stage="decode", seq=seq)
            return self._send_on(st, wire.encode_error(str(e), seq=seq))
        trace = req["trace"]
        # decode wall captured now, EMITTED only for a FRESH frame (below):
        # a replayed or BUSY-resent frame decodes again but must not
        # duplicate its decode segment under the same trace id
        decode_s = time.perf_counter() - t0
        tenant = req["tenant"] or self.default_tenant
        if tenant is None:
            st.stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="route")
            return self._send_on(st, wire.encode_error(
                "frame names no tenant and the gateway has no default — "
                "set the tenant field or start with --tenant", seq=seq))
        # the dedup window, membership-based: a seq already CACHED answers
        # from the reply cache, one still PENDING adopts the in-flight
        # future, one below the eviction floor is unknowable — and anything
        # else is FRESH, whatever its ordering (a restarted gateway sees a
        # resumed producer's replay start mid-sequence; a BUSY-deferred
        # retransmit arrives after its successors; both are legitimate).
        # A fresh frame is claimed ATOMICALLY with its classification: the
        # relay future goes into pending inside the same lock hold, so a
        # replay racing in on another connection adopts the relay instead
        # of classifying fresh and double-dispatching the block
        relay = None
        with sess.lock:
            cached = sess.replies.get(seq)
            pending = sess.pending.get(seq) if cached is None else None
            if cached is not None or pending is not None:
                action = "replay"
            elif seq < sess.evicted_below:
                action = "evicted"
            else:
                with st.lock:
                    busy = st.inflight >= self.max_inflight_replies
                    if not busy:
                        st.inflight += 1
                if busy:
                    action = "busy"
                else:
                    action = "fresh"
                    relay = SlimFuture()
                    sess.pending[seq] = (relay, req["date_idx"], trace)
                    sess.last_seq = max(sess.last_seq, seq)
                    sess.frames += 1
        if action == "replay":
            # at-least-once-submit, exactly-once-serve
            obs_count("serve/gateway_replays")
            if cached is not None:
                with sess.lock:
                    sess.replayed_from_cache += 1
                return self._send_on(st, cached)
            # adopt the orphan: the frame was submitted on a connection
            # that died; its reply lands HERE when the block resolves
            fut, date_idx, a_trace = pending
            fut.add_done_callback(
                lambda f: self._reply_ready(sess, seq, date_idx, st, f,
                                            trace=a_trace))
            return True
        if action == "evicted":
            st.stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="sequence")
            return self._send_on(st, wire.encode_error(
                f"seq {seq} was served but evicted from the "
                f"{self.reply_cache}-frame reply cache — shrink the client "
                "replay window or grow the gateway's reply_cache", seq=seq))
        if action == "busy":
            # backpressure, not shedding: nothing was admitted, nothing died
            obs_count("serve/gateway_busy")
            flight.record("busy", seq=seq)
            return self._send_on(st, wire.encode_busy(
                seq, f"{self.max_inflight_replies} replies in flight on "
                     "this connection — wait for acks and resend"))
        if trace is not None:
            # the first serving-chain segment, once per ADMITTED frame
            emit_trace_span("trace/decode", trace[0], trace[1], decode_s,
                            attrs={"bytes": len(frame), "seq": seq})
        return self._submit_v2(req, seq, relay, sess, st)

    def _submit_v2(self, req: dict, seq: int, relay, sess: _Session,
                   st: _Conn) -> bool:
        """Dispatch a CLAIMED fresh frame: the relay future is already in
        the session's pending window (adoptable by replays), the host's
        block future chains into it."""
        date_idx = req["date_idx"]
        trace = req["trace"]
        relay.add_done_callback(
            lambda f: self._reply_ready(sess, seq, date_idx, st, f,
                                        claimer=True, trace=trace))
        tenant = req["tenant"] or self.default_tenant
        try:
            fut = self.host.submit_block(tenant, date_idx,
                                         req["states"], req["prices"],
                                         req["deadlines"], trace=trace)
        except Exception as e:  # orp: noqa[ORP009] -- emitted: _reply_ready counts it AND ships it as an ERROR frame
            relay.set_exception(e)
            return True
        with self._lock:
            self._submitted_frames += 1
            n_sub = self._submitted_frames
            # the session saw traffic: keep it off the LRU eviction edge
            # (HELLO-only refresh would evict the BUSIEST long-lived
            # session first, silently breaking its replay guarantee)
            if sess.token in self._sessions:
                self._sessions.move_to_end(sess.token)
        fut.add_done_callback(lambda f: _chain(relay, f))
        inj = inject.active()
        if inj is not None and inj.gateway_kill(n_sub):
            # the chaos drill's process death: frame k is ADMITTED (the
            # nastiest point — the producer will never see its reply and
            # must replay it against whatever comes up on this port next)
            self.abort()
            return False
        return True

    def _reply_ready(self, sess: _Session, seq: int, date_idx: int,
                     st: _Conn, fut, claimer: bool = False,
                     trace=None) -> None:
        """Done-callback of a sequenced block future: encode the reply ONCE
        into the session's cache, then hand it to ``st``'s writer thread (a
        dead connection just leaves it cached for the replay). Runs on the
        resolving thread — encode + enqueue only, so a slow consumer never
        stalls the dispatch loop. ``claimer`` marks the callback installed
        at claim time: EXACTLY that one settles the admitting connection's
        inflight/ledger accounting (an adopting connection's callback may
        resolve first, but it never incremented anything). The whole
        callback is bracketed by the ``_replying`` counter so a graceful
        drain waits the send out, not just the pending-delete."""
        with self._lock:
            self._replying += 1
        try:
            self._reply_ready_inner(sess, seq, date_idx, st, fut, claimer,
                                    trace)
        finally:
            with self._lock:
                self._replying -= 1

    def _reply_ready_inner(self, sess: _Session, seq: int, date_idx: int,
                           st: _Conn, fut, claimer: bool, trace) -> None:
        err = fut.exception()
        if err is not None:
            reply = wire.encode_error(f"{type(err).__name__}: {err}",
                                      seq=seq)
            n = 0
        else:
            result: BlockResult = fut.result()
            t0 = time.perf_counter()
            timing = None
            if trace is not None and result.timing is not None:
                # the compact server-timing block rides the reply's trace
                # extension back to the producer
                timing = (trace[0], *result.timing)
            reply = wire.encode_reply(result, date_idx=date_idx, seq=seq,
                                      timing=timing)
            n = result.n_rows
            if trace is not None and claimer:
                # the last serving-chain segment: reply encode wall. Only
                # the CLAIMER's callback emits it — an adopting replay's
                # racing callback re-encodes the same frame and would
                # duplicate the segment in the trace
                emit_trace_span("trace/encode", trace[0], trace[1],
                                time.perf_counter() - t0,
                                attrs={"rows": n, "seq": seq})
        with sess.lock:
            first = seq in sess.pending
            if first:
                del sess.pending[seq]
                sess.replies[seq] = reply
                sess.rows += n
                while len(sess.replies) > self.reply_cache:
                    old_seq, _ = sess.replies.popitem(last=False)
                    sess.evicted_below = max(sess.evicted_below,
                                             old_seq + 1)
            else:
                # the racing callback already cached it; send that encoding
                reply = sess.replies.get(seq, reply)
        if claimer:
            with st.lock:
                st.inflight -= 1
                if err is not None:
                    st.stats["errors"] += 1
                else:
                    st.stats["rows"] += n
            if err is not None:
                obs_count("serve/gateway_errors", stage="serve")
            else:
                obs_count("serve/gateway_rows", n, sink_event=False)
            inj = inject.active()
            if inj is not None:
                try:
                    inj.fire("gateway/reply")
                except Exception:  # orp: noqa[ORP009] -- the injected reset IS the emission: the producer must recover from it
                    # connection-reset-after-submit-before-reply: the reply
                    # stays cached; the producer's replay is answered from it
                    try:
                        st.sock.close()
                    except OSError:  # orp: noqa[ORP009] -- best-effort close of the injected reset
                        pass
                    return
        self._enqueue_reply(st, reply)

    def _handle_request_v1(self, frame: bytes, st: _Conn) -> bool:
        """The pre-sequencing path, unchanged semantics: decode →
        submit_block → block on the future → reply inline. No session, no
        dedup — a v1 producer that loses its connection cannot know which
        rows landed (exactly the gap the v2 handshake closes)."""
        stats = st.stats
        t0 = time.perf_counter()
        try:
            req = wire.decode_request(frame)
        except wire.WireError as e:
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="decode")
            flight.record("wire_error", stage="decode")
            return self._send_on(st, wire.encode_error(str(e)))
        trace = req["trace"]
        if trace is not None:
            emit_trace_span("trace/decode", trace[0], trace[1],
                            time.perf_counter() - t0,
                            attrs={"bytes": len(frame)})
        tenant = req["tenant"] or self.default_tenant
        if tenant is None:
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="route")
            return self._send_on(st, wire.encode_error(
                "frame names no tenant and the gateway has no default — "
                "set the tenant field or start with --tenant"))
        try:
            fut = self.host.submit_block(tenant, req["date_idx"],
                                         req["states"], req["prices"],
                                         req["deadlines"], trace=trace)
            with self._lock:
                self._submitted_frames += 1
            result: BlockResult = fut.result(timeout=self.reply_timeout_s)
        except Exception as e:  # orp: noqa[ORP009] -- emitted: counted AND shipped to the client as an ERROR frame
            stats["errors"] += 1
            obs_count("serve/gateway_errors", stage="serve")
            return self._send_on(st, wire.encode_error(
                f"{type(e).__name__}: {e}"))
        n = result.n_rows
        stats["rows"] += n
        obs_count("serve/gateway_rows", n, sink_event=False)
        t0 = time.perf_counter()
        timing = (None if trace is None or result.timing is None
                  else (trace[0], *result.timing))
        reply = wire.encode_reply(result, date_idx=req["date_idx"],
                                  timing=timing)
        if trace is not None:
            emit_trace_span("trace/encode", trace[0], trace[1],
                            time.perf_counter() - t0, attrs={"rows": n})
        return self._send_on(st, reply)

    def _send_on(self, st: _Conn, frame: bytes) -> bool:
        """One frame onto the wire from the HANDLER thread (pongs, errors,
        cached replays, v1 replies): synchronous, resumable, bounded."""
        with st.send_lock:
            return self._send_bytes(st, frame)

    def _send_bytes(self, st: _Conn, frame: bytes) -> bool:
        """Resumable bounded send (call with ``st.send_lock`` held). Each
        ``send`` attempt is bounded by the socket's poll timeout — NEVER by
        mutating the shared socket timeout, which would race the handler's
        recv poll and stretch stall eviction to the send bound — with the
        offset carried across attempts (a partial write is resumed, never a
        torn stream) and the WHOLE frame bounded by ``reply_timeout_s``.
        Any failure closes the connection (a sequenced producer reconnects
        and is answered from the reply cache)."""
        data = _LEN.pack(len(frame)) + frame
        view = memoryview(data)
        off = 0
        deadline = time.perf_counter() + self.reply_timeout_s
        try:
            while off < len(data):
                try:
                    off += st.sock.send(view[off:])  # orp: noqa[ORP014] -- poll timeout set at accept; the loop carries its own reply_timeout_s deadline
                except socket.timeout:
                    if time.perf_counter() > deadline:
                        raise OSError(  # orp: noqa[ORP016] -- the enclosing except OSError emits serve/gateway_errors{stage=send} + the flight record three lines down
                            "reply send exceeded reply_timeout_s") from None
            return True
        except OSError:
            obs_count("serve/gateway_errors", stage="send")
            flight.record("wire_error", stage="send")
            st.dead = True
            try:
                st.sock.close()
            except OSError:  # orp: noqa[ORP009] -- already dead; the close was the response
                pass
            return False

    def _enqueue_reply(self, st: _Conn, frame: bytes) -> None:
        """Hand a block reply to the connection's writer thread. Called
        from the RESOLVING thread (`_reply_ready` is a block-future done
        callback, which runs on the batcher worker): the enqueue is the
        only work done there — a consumer that stops reading stalls its
        own writer, never the dispatch loop. ``_replying`` covers the
        enqueued-but-unsent window so a graceful drain flushes it."""
        with self._lock:
            self._replying += 1
        with st.cv:
            st.outbox.append(frame)
            if st.writer is None:
                st.writer = threading.Thread(
                    target=self._writer_loop, args=(st,),
                    name="orp-gateway-writer", daemon=True)
                st.writer.start()
            st.cv.notify()

    def _writer_loop(self, st: _Conn) -> None:
        while True:
            with st.cv:
                while not st.outbox:
                    if st.dead or self._closed.is_set():
                        # retire under the cv: a late enqueue either sees
                        # writer=None (starts a fresh one that fail-fast
                        # flushes) or a live writer that will see its item
                        st.writer = None
                        return
                    st.cv.wait(0.25)
                frame = st.outbox.popleft()
            try:
                with st.send_lock:
                    self._send_bytes(st, frame)
            finally:
                with self._lock:
                    self._replying -= 1

    # -- introspection / lifecycle -------------------------------------------

    def metrics_text(self) -> str:
        """The live Prometheus exposition this process can honestly serve:
        the host registry (tenant serving series + the pre-interned core
        gateway series) plus, when an obs session is active with a DIFFERENT
        registry, that one too. This is what the METRICS wire kind and the
        ``--metrics-port`` HTTP endpoint both answer — ``metrics.prom``
        from the LIVE process, no clean exit required."""
        regs = [self.host.registry]
        st = obs_state()
        if st is not None and st.registry is not regs[0]:
            regs.append(st.registry)
        return "".join(prometheus_text(r) for r in regs)

    def health_report(self, *, dump_flight: bool = False,
                      route=None) -> dict:
        """Compact JSON health document (the HEALTH wire kind): draining
        flag, session count, cumulative ledgers, per-tenant pending, and
        the flight-ring state. ``dump_flight=True`` (a HEALTH request with
        ``{"dump_flight": true}`` — what ``orp doctor --metrics`` sends)
        additionally DUMPS the flight ring when the recorder is armed: a
        probe against a sick gateway leaves the evidence on disk. A plain
        probe (``orp top``'s per-refresh HEALTH) never writes — a
        read-only dashboard must not cause disk I/O in the serving
        process.

        When the host is a fleet router (``serve/fleet.py::FleetHost``)
        the document additionally carries ``routing``: the routing-table
        version, healthy set, per-replica health ages and the mapping of
        a tenant sample (``route`` — a HEALTH request with ``{"route":
        [...names...]}``; the default sample when omitted) — what ``orp
        doctor --fleet`` compares across gateway processes."""
        dump = flight.RECORDER.dump() if dump_flight else None
        with self._lock:
            sessions = len(self._sessions)
        tenants = {
            name: {k: s[k] for k in ("live", "pending", "version")}
            for name, s in self.host.stats().items()
        }
        routing = None
        route_sample = getattr(self.host, "route_sample", None)
        if route_sample is not None:
            routing = route_sample(route)
        return {
            **({"routing": routing} if routing is not None else {}),
            "draining": self._draining.is_set(),
            "aborted": self.aborted.is_set(),
            "sessions": sessions,
            "totals": self.totals(),
            "tenants": tenants,
            "flight_recorded": flight.RECORDER.recorded,
            "flight_dump": None if dump is None else str(dump),
        }

    def stats(self) -> dict:
        """Live per-connection ledgers: ``{conn_id: {peer, frames, rows,
        errors}}``."""
        with self._lock:
            return {cid: dict(s) for cid, s in self._conns.items()}

    def totals(self) -> dict:
        """The cumulative ledger, retired connections included:
        ``frames``/``rows``/``errors`` plus ``submitted_frames`` (blocks
        that reached the host — the exactly-once-serve count a chaos drill
        pins)."""
        with self._lock:
            t = dict(self._retired)
            for s in list(self._conns.values()) + list(self._recent_retired):
                for k in ("frames", "rows", "errors"):
                    t[k] += s[k]
            t["submitted_frames"] = self._submitted_frames
            t["replayed_from_cache"] = sum(
                s.replayed_from_cache for s in self._sessions.values())
        return t

    def _pending_frames(self) -> int:
        with self._lock:
            sessions = list(self._sessions.values())
        n = 0
        for s in sessions:
            with s.lock:
                n += len(s.pending)
        return n

    def close(self, timeout: float = 5.0, *, successor=None) -> None:
        """Graceful drain: stop accepting, answer NEW frames with REDIRECT
        (when ``successor=(host, port)`` names where traffic should go) or
        a draining ERROR, flush every in-flight reply, then close.

        The drain-and-redirect contract: a producer mid-stream loses zero
        rows — admitted frames finish and their replies flush here, refused
        frames carry their seq so the producer replays them against the
        successor."""
        if self._closed.is_set():
            return
        if successor is not None:
            self._redirect = (str(successor[0]), int(successor[1]))
        self._draining.set()
        try:
            self._sock.close()
        except OSError:  # orp: noqa[ORP009] -- already closed; the drain continues
            pass
        self._acceptor.join(timeout)
        # flush: every admitted frame resolves AND its reply hits the wire
        # (_replying covers the pending-delete → send window) before the
        # handlers are told to stop
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with self._lock:
                replying = self._replying
            if not replying and not self._pending_frames():
                break
            time.sleep(0.005)
        self._closed.set()
        with self._lock:
            handlers = list(self._handlers)
        for t in handlers:
            t.join(timeout)

    def abort(self) -> None:
        """Simulated process death (the chaos drill's kill switch): close
        the listener and every live connection immediately — no drain, no
        flush; sessions die with the object exactly as they would with the
        process."""
        self._closed.set()
        self._draining.set()
        try:
            self._sock.close()
        except OSError:  # orp: noqa[ORP009] -- already closed; the abort continues
            pass
        with self._lock:
            socks = list(self._csocks.values())
        for s in socks:
            try:
                s.close()
            except OSError:  # orp: noqa[ORP009] -- racing the handler's own close; nothing to emit
                pass
        self.aborted.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class GatewayClient:
    """The minimal ``orp-ingest`` v1 client: one TCP connection, columnar
    frames in, :class:`BlockResult` out, one frame in flight. The
    five-line usage::

        from orp_tpu.serve.gateway import GatewayClient
        with GatewayClient("127.0.0.1", 7433) as c:
            res = c.submit_block("desk-a", date_idx=3, states=feats)
        print(res.phi, res.status)

    ``timeout_s`` bounds the CONNECT and EVERY recv: a dead-but-accepting
    endpoint surfaces as ``socket.timeout`` (an ``OSError``) within it,
    never an indefinite block. No replay, no sequencing — for delivery
    guarantees across reconnects use
    :class:`~orp_tpu.serve.client.ResilientGatewayClient`."""

    def __init__(self, addr: str, port: int, *, timeout_s: float = 60.0):
        self.timeout_s = float(timeout_s)
        self._sock = socket.create_connection((addr, int(port)),
                                              timeout=self.timeout_s)
        # create_connection seeds the timeout, but state it explicitly: the
        # per-recv bound is this class's contract, not an inherited default
        self._sock.settimeout(self.timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()  # one in-flight frame per connection

    def submit_block(self, tenant: str, date_idx: int, states, prices=None,
                     deadlines=None, *,
                     deadline_ms: float | None = None,
                     trace=None) -> BlockResult:
        """Ship one block and block on its columnar reply. Raises
        :class:`GatewayError` with the server's flag-speak message when the
        server refused the frame (or the serve itself failed). ``trace``:
        an optional ``(trace_id, parent_span)`` pair (``obs.new_trace()``)
        stamped into the frame — the serving process links its segment
        spans under it and the returned :class:`BlockResult` carries the
        server-timing pair in ``timing``."""
        frame = wire.encode_request(tenant, date_idx, states, prices,
                                    deadlines, deadline_ms=deadline_ms,
                                    trace=trace)
        reply = self._roundtrip(frame)
        if wire.decode_kind(reply) == wire.KIND_ERROR:
            raise GatewayError(wire.decode_error(reply))
        return wire.decode_reply(reply)

    def ping(self) -> bool:
        """One PING round trip — the doctor probe's liveness check."""
        reply = self._roundtrip(wire.encode_ping())
        return wire.decode_kind(reply) == wire.KIND_PONG

    def metrics(self) -> str:
        """Scrape the gateway's LIVE Prometheus exposition over the wire
        (the METRICS kind) — what ``orp top`` and ``orp doctor --metrics``
        read."""
        reply = self._roundtrip(wire.encode_metrics())
        if wire.decode_kind(reply) == wire.KIND_ERROR:
            raise GatewayError(wire.decode_error(reply))
        return wire.decode_metrics(reply)

    def health(self, *, dump_flight: bool = False, route=None) -> dict:
        """One HEALTH round trip: the gateway's JSON health document
        (draining flag, ledgers, per-tenant pending). ``dump_flight=True``
        asks the serving process to dump its flight recorder (when armed)
        — the doctor's black-box hook; plain probes never cause writes.
        ``route`` (a list of tenant names) asks a FLEET gateway for its
        routing view of that sample (``routing`` in the document)."""
        ask = {}
        if dump_flight:
            ask["dump_flight"] = True
        if route is not None:
            ask["route"] = list(route)
        reply = self._roundtrip(wire.encode_health(ask or None))
        if wire.decode_kind(reply) == wire.KIND_ERROR:
            raise GatewayError(wire.decode_error(reply))
        return wire.decode_health(reply)

    def _roundtrip(self, frame: bytes) -> bytes:
        with self._lock:
            _send_frame(self._sock, frame)
            reply = _recv_frame(self._sock)
        if reply is None:
            raise GatewayError("connection closed by the gateway mid-reply")
        return reply

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:  # orp: noqa[ORP009] -- best-effort close; nothing to emit
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
