"""L8 serving: exportable hedge-policy bundles + a batched evaluation engine.

The training pipelines (L7) end with a ``PipelineResult`` — per-date trained
params plus in-sample ledgers — that dies with the process. This layer turns
that into production artifacts and serves them:

- ``bundle``  — export/load a trained policy as an on-disk bundle
  (orbax params + JSON metadata + run-fingerprint guard);
- ``engine``  — jit-compiled ``evaluate(date_idx, states) -> (phi, psi, v)``
  with shape-bucketed executable caching (arbitrary request sizes hit a
  small fixed set of compiled programs) and a non-blocking
  ``evaluate_async`` twin (dispatch now, block later) the batcher
  overlaps;
- ``batcher`` — async continuous batching: a dispatch loop that admits
  in-flight requests into the next bucket while the previous batch
  executes on device (double-buffered submit riding JAX's async
  dispatch); an optional ``orp_tpu.guard.GuardPolicy`` adds per-request
  deadlines, watermark load shedding and transient-dispatch retries;
- ``host``    — multi-tenant serving: many policy bundles in one process
  under an LRU engine cap, per-tenant quotas (``Rejection``
  ``reason="quota"``), SLO burn-rate evaluation off the obs registry,
  and canary-gated hot bundle reload (``reload_tenant``: the candidate
  must reproduce pinned probe rows bitwise before taking traffic;
  rejects roll back to the serving bundle);
- ``ingest``  — the columnar block lane: ``submit_block`` admits N rows
  under one lock pass with ONE future; answers are ``BlockResult``
  columns plus a per-row status column (served / shed-deadline /
  shed-watermark / shed-quota) — guard semantics exact but vectorized;
- ``wire``    — ``orp-ingest-v2``: versioned fixed-width little-endian
  frames, ``np.frombuffer``/``tobytes`` only, malformed frames refused
  with structured error frames in flag-speak; v2 adds per-session frame
  sequencing, the HELLO/RESUME handshake and the BUSY/REDIRECT delivery
  frames (v1 frames still accepted, without guarantees);
- ``gateway`` — the length-prefixed TCP ingest front (``orp
  serve-gateway``): decode → ``submit_block`` → encode is the whole
  per-frame Python bill, amortized over the block's rows; sessions
  deduplicate replayed frames (bounded reply cache), a partial-frame read
  deadline evicts stalled clients, per-connection in-flight bounds answer
  BUSY backpressure, and ``close(successor=...)`` drains-and-redirects a
  live producer with zero lost rows;
- ``client``  — ``ResilientGatewayClient``: the delivery-guaranteed
  producer — bounded replay buffer of unacknowledged sequenced frames,
  reconnect with guard-policy backoff, RESUME + replay (at-least-once-
  submit, exactly-once-serve), BUSY retransmit and REDIRECT following;
- ``health``  — the stuck-dispatch watchdog (``GuardPolicy.hard_wall_ms``:
  hung batches force-fail, feed the engine's circuit breaker, retry on a
  path that can answer) and the ``orp doctor`` environment/bundle probe;
- ``metrics`` — p50/p95/p99 latency + throughput counters + dispatch-
  amortisation gauges (batch occupancy, dispatches per request);
- ``bench``   — the ``serve-bench`` mode (mixed-size engine schedule,
  batcher burst, concurrency sweep) emitting ``BENCH_serve.json``.
"""

from orp_tpu.serve.batcher import MicroBatcher
from orp_tpu.serve.bench import serve_bench, write_bench_record
from orp_tpu.serve.bundle import PolicyBundle, export_bundle, load_bundle
from orp_tpu.serve.client import ResilientGatewayClient
from orp_tpu.serve.engine import HedgeEngine, PendingEval
from orp_tpu.serve.gateway import (FrameStall, GatewayClient, GatewayError,
                                   ServeGateway)
from orp_tpu.serve.health import DispatchWatchdog, doctor_report
from orp_tpu.serve.host import (CanaryRejected, ServeHost, SloPolicy,
                                burn_rate)
from orp_tpu.serve.ingest import (SERVED, SHED_DEADLINE, SHED_QUOTA,
                                  SHED_WATERMARK, STATUS_NAMES, BlockResult,
                                  concat_results)
from orp_tpu.serve.megakernel import loop_of_buckets, mixed_head_forward
from orp_tpu.serve.metrics import ServingMetrics
from orp_tpu.serve.precision import (TIERS, PrecisionPolicy,
                                     normalize_precision)
from orp_tpu.serve.ragged import BucketPlanner
from orp_tpu.serve.scrape import (MetricsServer, parse_prometheus,
                                  render_top, top_snapshot)

__all__ = [
    "BlockResult",
    "BucketPlanner",
    "CanaryRejected",
    "DispatchWatchdog",
    "FrameStall",
    "GatewayClient",
    "GatewayError",
    "HedgeEngine",
    "MetricsServer",
    "MicroBatcher",
    "PendingEval",
    "PolicyBundle",
    "PrecisionPolicy",
    "ResilientGatewayClient",
    "SERVED",
    "SHED_DEADLINE",
    "SHED_QUOTA",
    "SHED_WATERMARK",
    "STATUS_NAMES",
    "ServeGateway",
    "ServeHost",
    "ServingMetrics",
    "SloPolicy",
    "TIERS",
    "burn_rate",
    "concat_results",
    "doctor_report",
    "export_bundle",
    "load_bundle",
    "loop_of_buckets",
    "mixed_head_forward",
    "normalize_precision",
    "parse_prometheus",
    "render_top",
    "serve_bench",
    "top_snapshot",
    "write_bench_record",
]
