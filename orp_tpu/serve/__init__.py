"""L8 serving: exportable hedge-policy bundles + a batched evaluation engine.

The training pipelines (L7) end with a ``PipelineResult`` — per-date trained
params plus in-sample ledgers — that dies with the process. This layer turns
that into production artifacts and serves them:

- ``bundle``  — export/load a trained policy as an on-disk bundle
  (orbax params + JSON metadata + run-fingerprint guard);
- ``engine``  — jit-compiled ``evaluate(date_idx, states) -> (phi, psi, v)``
  with shape-bucketed executable caching (arbitrary request sizes hit a
  small fixed set of compiled programs);
- ``batcher`` — micro-batching: coalesce many small synchronous requests
  into one device batch (max-batch / max-wait policy); an optional
  ``orp_tpu.guard.GuardPolicy`` adds per-request deadlines, watermark
  load shedding and transient-dispatch retries;
- ``metrics`` — p50/p95/p99 latency + throughput counters;
- ``bench``   — the ``serve-bench`` mode emitting ``BENCH_serve.json``.
"""

from orp_tpu.serve.batcher import MicroBatcher
from orp_tpu.serve.bench import serve_bench, write_bench_record
from orp_tpu.serve.bundle import PolicyBundle, export_bundle, load_bundle
from orp_tpu.serve.engine import HedgeEngine
from orp_tpu.serve.metrics import ServingMetrics

__all__ = [
    "HedgeEngine",
    "MicroBatcher",
    "PolicyBundle",
    "ServingMetrics",
    "export_bundle",
    "load_bundle",
    "serve_bench",
    "write_bench_record",
]
