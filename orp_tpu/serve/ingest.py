"""Columnar ingest plane: move requests in columns, not Python objects.

PR 7 measured the continuous batcher's ceiling precisely: once dispatch is
amortized and the device overlapped, the per-request *host-language* cost —
one ``submit()`` call, one ``SlimFuture``, one ``_Request``, one dict-group
insert per row — serializes the whole tier at ~6µs/request. That is the
Orca lesson (Yu et al., PAPERS.md) taken one level down: continuous
batching amortizes the DEVICE over requests; the next 10x amortizes the
HOST over rows. Every production inference gateway lands on the same fix —
struct-of-arrays request blocks whose per-row cost is a NumPy slice, with
all Python object churn paid once per block:

- a **block** is N rows for one rebalance date: a contiguous ``(n,
  n_features)`` feature matrix, an optional ``(n, k)`` price matrix, an
  optional per-row float64 deadline column — and exactly ONE
  :class:`~orp_tpu.serve.batcher.SlimFuture` for all N rows;
- guard semantics stay exact but become **vectorized**: deadline expiry is
  a mask compare on the deadline column, watermark/quota shed the TAIL
  rows of a block as a slice — never a per-row ``Rejection`` object;
- the answer is a :class:`BlockResult`: contiguous ``phi``/``psi``/
  ``value`` columns plus a per-row ``status`` column (:data:`SERVED` /
  :data:`SHED_DEADLINE` / :data:`SHED_WATERMARK` / :data:`SHED_QUOTA`),
  bitwise-equal on served rows to N per-request submits of the same rows
  (pinned in ``tests/test_ingest.py``).

Lint rule ORP013 enforces the discipline this module exists for: no
``for`` loop over rows constructing objects, appending futures or calling
``submit`` inside ingest-path code under ``serve/``.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from orp_tpu.obs import count as obs_count
from orp_tpu.obs import emit_trace_spans, flight
from orp_tpu.obs import observe as obs_observe

# per-row status codes (the BlockResult.status column / the wire's status
# column — a u8, so the codec ships it with one tobytes)
SERVED = 0
SHED_DEADLINE = 1
SHED_WATERMARK = 2
SHED_QUOTA = 3

STATUS_NAMES = {
    SERVED: "served",
    SHED_DEADLINE: "shed-deadline",
    SHED_WATERMARK: "shed-watermark",
    SHED_QUOTA: "shed-quota",
}

_SHED_REASON = {SHED_DEADLINE: "deadline", SHED_WATERMARK: "watermark",
                SHED_QUOTA: "quota"}


@dataclasses.dataclass(frozen=True)
class BlockResult:
    """The columnar answer to a ``submit_block``: one contiguous column per
    output, one status byte per row. Rows whose status is not
    :data:`SERVED` carry zeros in the value columns — the status column,
    not a sentinel value, is the contract (a legitimately-served phi can be
    0.0).

    ``phi``/``psi``: ``(n,)`` hedge ratios; ``value``: ``(n,)`` portfolio
    values or None when the block carried no prices; ``status``: ``(n,)``
    uint8 of status codes (:data:`STATUS_NAMES`); ``timing``: the compact
    server-timing block of a TRACED block — ``(queue_age_s, dispatch_s)``,
    None on every untraced path (the wire carries it back to the producer
    as the reply's 16-byte trace extension).
    """

    phi: np.ndarray
    psi: np.ndarray
    value: np.ndarray | None
    status: np.ndarray
    timing: tuple[float, float] | None = None

    @property
    def n_rows(self) -> int:
        return int(self.status.shape[0])

    @property
    def served_mask(self) -> np.ndarray:
        """Boolean column: True where the row was served."""
        return self.status == SERVED

    @property
    def n_served(self) -> int:
        return int(np.count_nonzero(self.status == SERVED))

    def shed_counts(self) -> dict[str, int]:
        """Rows per non-served status name (zero-count statuses omitted)."""
        codes, counts = np.unique(self.status, return_counts=True)
        return {STATUS_NAMES[int(c)]: int(k)
                for c, k in zip(codes, counts) if int(c) != SERVED}


def all_shed_result(n: int, code: int, *, has_value: bool,
                    dtype=np.float32) -> BlockResult:
    """A block that never reached the device: every row shed with ``code``
    (quota at the host, watermark at submit, deadline for a block that
    expired whole)."""
    z = np.zeros(n, dtype)
    return BlockResult(
        phi=z, psi=z.copy(),
        value=np.zeros(n, dtype) if has_value else None,
        status=np.full(n, code, np.uint8),
    )


def concat_results(results) -> BlockResult:
    """Stack a sequence of :class:`BlockResult`\\ s into one (the drill /
    bench shape: many blocks, one ledger to compare bitwise). ``value`` is
    kept only when every block carries it."""
    results = list(results)
    if not results:
        raise ValueError("concat_results needs at least one BlockResult")
    has_value = all(r.value is not None for r in results)
    return BlockResult(
        phi=np.concatenate([r.phi for r in results]),
        psi=np.concatenate([r.psi for r in results]),
        value=(np.concatenate([r.value for r in results])
               if has_value else None),
        status=np.concatenate([r.status for r in results]),
    )


def merge_tail_shed(head: BlockResult, n_tail: int, code: int) -> BlockResult:
    """Extend ``head`` (the admitted prefix of a block) with ``n_tail``
    tail rows shed as ``code`` — the quota/watermark tail-slice semantics:
    the shed rows were never objects, so the merge is two concatenates and
    a fill."""
    if n_tail <= 0:
        return head
    tail = all_shed_result(n_tail, code, has_value=head.value is not None,
                           dtype=head.phi.dtype)
    return BlockResult(
        phi=np.concatenate([head.phi, tail.phi]),
        psi=np.concatenate([head.psi, tail.psi]),
        value=(None if head.value is None
               else np.concatenate([head.value, tail.value])),
        status=np.concatenate([head.status, tail.status]),
        timing=head.timing,
    )


class Block:
    """One admitted request block as the batcher tracks it: the columns,
    the per-row status ledger, and the single future the whole block
    resolves through. All mutation is vectorized — the ORP013 contract.

    ``deadlines`` is an absolute-``perf_counter`` float64 column (or None:
    rows never expire); ``status`` starts all-:data:`SERVED` and rows are
    struck off by slice (watermark tail at submit) or mask (deadline at
    admit) before dispatch. ``features``/``prices`` keep the FULL n rows —
    the live subset is sliced out only at dispatch, so the clean path
    (nothing shed) dispatches the caller's own contiguous arrays with zero
    copies.
    """

    __slots__ = ("date_idx", "features", "prices", "future", "submitted_at",
                 "deadlines", "status", "n", "trace", "t_admit",
                 "t_dispatch")

    def __init__(self, date_idx: int, features, prices, future,
                 submitted_at: float, deadlines, trace=None):
        self.date_idx = int(date_idx)
        self.features = features            # (n, n_features), contiguous
        self.prices = prices                # (n, k) or None
        self.future = future                # ONE SlimFuture for the block
        self.submitted_at = submitted_at
        self.deadlines = deadlines          # (n,) float64 absolute, or None
        self.n = int(features.shape[0])
        self.status = np.zeros(self.n, np.uint8)
        # distributed-trace context: (trace_id, parent_span) stamped by the
        # producer and carried through the batcher so the admit/dispatch/
        # resolve instants can be attributed. None (the untraced default)
        # keeps every stamp behind ONE `is not None` test per block
        self.trace = trace
        self.t_admit = None
        self.t_dispatch = None

    @property
    def n_live(self) -> int:
        return int(np.count_nonzero(self.status == SERVED))

    def shed_tail(self, keep: int, code: int) -> int:
        """Watermark/quota semantics: strike every row past ``keep`` (that
        is still live) with ``code``; returns how many rows were struck."""
        tail = self.status[max(0, keep):]
        struck = tail == SERVED
        tail[struck] = code
        return int(np.count_nonzero(struck))

    def mask_expired(self, now: float) -> int:
        """Deadline semantics, vectorized: one compare against the deadline
        column strikes every live row whose deadline has passed; returns
        how many rows were struck."""
        if self.deadlines is None:
            return 0
        expired = (self.status == SERVED) & (self.deadlines < now)
        k = int(np.count_nonzero(expired))
        if k:
            self.status[expired] = SHED_DEADLINE
        return k

    def live_columns(self):
        """The dispatchable columns: ``(features, prices)`` restricted to
        live rows. The nothing-shed fast path returns the stored arrays
        themselves — no copy, no concatenate."""
        if self.n_live == self.n:
            return self.features, self.prices
        live = self.status == SERVED
        return (np.ascontiguousarray(self.features[live]),
                None if self.prices is None
                else np.ascontiguousarray(self.prices[live]))

    def emit_shed(self, code: int, n_rows: int) -> None:
        """Guard signals for ``n_rows`` struck with ``code`` — ONE counter
        bump (by row count) and ONE queue-age observation per block event,
        mirroring the per-request lane's ``guard/shed`` /
        ``serve/queue_age_seconds`` semantics at block cost."""
        if n_rows <= 0:
            return
        obs_count("guard/shed", n_rows, reason=_SHED_REASON[code],
                  lane="block")
        obs_observe("serve/queue_age_seconds",
                    time.perf_counter() - self.submitted_at, outcome="shed")
        flight.record("shed", reason=_SHED_REASON[code], rows=int(n_rows),
                      lane="block")

    def resolve_shed_only(self) -> None:
        """Resolve a block none of whose rows survived to dispatch (all
        quota/watermark/deadline) — zeros in every value column, the status
        column tells the story."""
        if self.future.set_running_or_notify_cancel():
            dt = self.features.dtype if self.features.dtype.kind == "f" \
                else np.float32
            z = np.zeros(self.n, dt)
            self.future.set_result(BlockResult(
                phi=z, psi=z.copy(),
                value=np.zeros(self.n, dt) if self.prices is not None else None,
                status=self.status,
            ))

    def trace_report(self, done: float) -> tuple[float, float]:
        """TRACED blocks only: emit the queue/dispatch/resolve trace spans
        (``obs.emit_trace_span`` — no-ops without a sink) and return the
        compact server-timing block ``(queue_age_s, dispatch_s)`` the
        reply's trace extension carries back to the producer. The segment
        walls are the batcher's own instants: submit → admit is the queue,
        admit → device submit is the dispatch stage, device submit →
        device-complete is the resolve (the stage whose job is to block)."""
        tid, parent = self.trace
        t_admit = self.t_admit if self.t_admit is not None \
            else self.submitted_at
        t_disp = self.t_dispatch if self.t_dispatch is not None else t_admit
        queue_s = max(0.0, t_admit - self.submitted_at)
        dispatch_s = max(0.0, done - t_disp)
        # ONE sink burst for the whole frame: the per-frame tracing budget
        # (BENCH_serve trace_overhead gate) is paid right here
        emit_trace_spans(tid, parent, (
            ("trace/queue", queue_s),
            ("trace/dispatch", max(0.0, t_disp - t_admit)),
            ("trace/resolve", dispatch_s),
        ))
        return (queue_s, dispatch_s)

    def resolve_served(self, phi, psi, value, timing=None) -> None:
        """Scatter the dispatched (live-row) results back into full-size
        columns and resolve the block's one future. The nothing-shed fast
        path hands the engine's arrays through untouched. ``timing`` is the
        traced block's server-timing pair (None untraced)."""
        if self.n_live == self.n:
            out = BlockResult(phi=phi, psi=psi, value=value,
                              status=self.status, timing=timing)
        else:
            live = self.status == SERVED
            full_phi = np.zeros(self.n, phi.dtype)
            full_psi = np.zeros(self.n, psi.dtype)
            full_phi[live] = phi
            full_psi[live] = psi
            full_value = None
            if value is not None:
                full_value = np.zeros(self.n, value.dtype)
                full_value[live] = value
            out = BlockResult(phi=full_phi, psi=full_psi, value=full_value,
                              status=self.status, timing=timing)
        if self.future.set_running_or_notify_cancel():
            self.future.set_result(out)


def as_deadline_column(deadlines, n: int, now: float,
                       default_s: float | None) -> np.ndarray | None:
    """Normalise a caller's ``deadlines`` argument — None, a scalar budget
    in seconds, or an ``(n,)`` per-row budget column — into the absolute
    float64 deadline column the admit-time mask compares against. With no
    per-row deadlines and no policy default, returns None (rows never
    expire)."""
    if deadlines is None:
        if default_s is None:
            return None
        return np.full(n, now + default_s, np.float64)
    col = np.asarray(deadlines, np.float64)
    if col.ndim == 0:
        return np.full(n, now + float(col), np.float64)
    if col.shape != (n,):
        raise ValueError(
            f"deadlines column has shape {col.shape}; expected ({n},) — one "
            "relative budget (seconds) per block row, or a scalar for all"
        )
    return now + col
