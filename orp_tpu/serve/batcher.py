"""Micro-batching: coalesce small synchronous requests into device batches.

The serving anti-pattern is one device dispatch per one-row request — launch
overhead dominates and the MXU runs at batch size 1. The standard fix (the
shape every production JAX/Triton/TF-Serving stack converges on) is a
micro-batcher: requests land on a queue, a worker drains it under a
``max_batch`` / ``max_wait_us`` policy, groups rows that can share an
executable (same rebalance date, same prices-presence), dispatches ONE
bucketed evaluation per group, and scatters the row slices back to each
caller's future.

Correctness contract: every request gets exactly the rows it submitted, in
the order it submitted them, bitwise-equal to a solo ``engine.evaluate`` of
the same rows padded into the same bucket family — the batcher changes
latency/throughput, never results. A failed dispatch propagates the
exception to every future in that group (not to unrelated groups).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from orp_tpu.obs import count as obs_count
from orp_tpu.obs import span
from orp_tpu.serve.metrics import ServingMetrics

_STOP = object()


@dataclasses.dataclass
class _Request:
    date_idx: int
    features: np.ndarray          # (rows, n_features)
    prices: np.ndarray | None     # (rows, k) or None
    future: Future
    submitted_at: float


class MicroBatcher:
    """Queue + worker thread in front of a ``HedgeEngine``.

    ``max_batch`` caps coalesced rows per dispatch; ``max_wait_us`` caps how
    long the first request of a batch waits for company. Small waits trade
    single-request latency for device throughput — at 200µs a burst of
    single-row requests rides one executable instead of hundreds.
    """

    def __init__(self, engine, *, max_batch: int = 1024,
                 max_wait_us: float = 200.0, metrics: ServingMetrics | None = None):
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        self.engine = engine
        self.max_batch = int(max_batch)
        self.max_wait_us = float(max_wait_us)
        self.metrics = metrics
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        # guards the closed-check + put pair: without it a submit racing
        # close() can land its request AFTER the stop sentinel, and that
        # future would never resolve
        self._submit_lock = threading.Lock()
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="orp-serve-batcher", daemon=True)
        self._worker.start()

    # -- client side ---------------------------------------------------------

    def submit(self, date_idx: int, states, prices=None) -> Future:
        """Enqueue one request; the Future resolves to ``(phi, psi, value)``
        for exactly these rows (``value`` None when ``prices`` is None)."""
        # promote scalars/rows to (rows, width) HERE: the worker indexes
        # .shape[0]/.shape[1] before any try block, so a lower-rank array
        # reaching it would kill the thread (and every pending future)
        feats = np.atleast_2d(np.asarray(states))
        pr = None if prices is None else np.atleast_2d(np.asarray(prices))
        fut: Future = Future()
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._q.put(
                _Request(int(date_idx), feats, pr, fut, time.perf_counter()))
        return fut

    def evaluate(self, date_idx: int, states, prices=None):
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(date_idx, states, prices).result()

    def close(self, timeout: float | None = 10.0) -> None:
        """Drain outstanding requests and stop the worker."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_STOP)
        self._worker.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- worker side ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            batch = [item]
            rows = item.features.shape[0]
            deadline = time.perf_counter() + self.max_wait_us * 1e-6
            stop_after = False
            while rows < self.max_batch:
                remaining = deadline - time.perf_counter()
                try:
                    nxt = (self._q.get(timeout=remaining) if remaining > 0
                           else self._q.get_nowait())
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                batch.append(nxt)
                rows += nxt.features.shape[0]
            self._dispatch(batch)
            if stop_after:
                return

    def _dispatch(self, batch: list[_Request]) -> None:
        # group rows that can share one executable dispatch: same date, same
        # feature width and same prices shape-presence. Width in the key
        # means a malformed request (wrong feature count) fails on ITS OWN
        # future with the engine's error instead of poisoning the concat of
        # an entire well-formed batch.
        groups: dict[tuple, list[_Request]] = {}
        for req in batch:
            key = (req.date_idx, req.features.shape[1],
                   None if req.prices is None else req.prices.shape[1])
            groups.setdefault(key, []).append(req)
        for (date_idx, _, pwidth), reqs in groups.items():
            has_prices = pwidth is not None
            try:
                feats = np.concatenate([r.features for r in reqs], axis=0)
                pr = (np.concatenate([r.prices for r in reqs], axis=0)
                      if has_prices else None)
                obs_count("serve/batcher_dispatches")
                obs_count("serve/batcher_coalesced_requests", len(reqs))
                with span("serve/batch", attrs={"requests": len(reqs),
                                                "rows": int(feats.shape[0])}):
                    # no set_result: evaluate() blocks device-side internally,
                    # so the span is already device-complete
                    phi, psi, value = self.engine.evaluate(date_idx, feats, pr)
            except Exception as e:  # noqa: BLE001 — delivered per-future
                for r in reqs:
                    if not r.future.set_running_or_notify_cancel():
                        continue
                    r.future.set_exception(e)
                continue
            done = time.perf_counter()
            off = 0
            for r in reqs:
                n = r.features.shape[0]
                sl = (phi[off:off + n], psi[off:off + n],
                      value[off:off + n] if has_prices else None)
                off += n
                if r.future.set_running_or_notify_cancel():
                    r.future.set_result(sl)
                if self.metrics is not None:
                    self.metrics.record(done - r.submitted_at, n)
