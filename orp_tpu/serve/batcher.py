"""Micro-batching: coalesce small synchronous requests into device batches.

The serving anti-pattern is one device dispatch per one-row request — launch
overhead dominates and the MXU runs at batch size 1. The standard fix (the
shape every production JAX/Triton/TF-Serving stack converges on) is a
micro-batcher: requests land on a queue, a worker drains it under a
``max_batch`` / ``max_wait_us`` policy, groups rows that can share an
executable (same rebalance date, same prices-presence), dispatches ONE
bucketed evaluation per group, and scatters the row slices back to each
caller's future.

Correctness contract: every request gets exactly the rows it submitted, in
the order it submitted them, bitwise-equal to a solo ``engine.evaluate`` of
the same rows padded into the same bucket family — the batcher changes
latency/throughput, never results. A failed dispatch propagates the
exception to every future in that group (not to unrelated groups).

Resilience (``orp_tpu/guard``, opt-in via a :class:`GuardPolicy`): the
single-worker design means one slow request head-of-line-blocks everything
behind it (BENCH_serve.json: the Python queue, not the device, is the
bottleneck). Under a policy the batcher therefore

- tracks every request's QUEUE AGE (``serve/queue_age_seconds`` histogram,
  labelled ``outcome=served|shed``) — the trace signal the shed decisions
  act on (the Dapper loop, PAPERS.md);
- enforces per-request DEADLINES: a request whose queue age passes its
  deadline is shed with a structured :class:`Rejection` through its future
  (``guard/shed{reason="deadline"}``), never served late — so the queue
  age of every *served* request is bounded by its deadline, whatever a
  slow neighbour did;
- applies ADMISSION CONTROL: past ``queue_watermark`` pending requests,
  the earliest-deadline (then oldest) request is shed at submit time
  (``guard/shed{reason="watermark"}``);
- RETRIES a dispatch that raised :class:`TransientDispatchError`, with
  bounded exponential backoff (``guard/retry``).

Without a policy none of this runs: the clean path is the pre-guard
batcher, and the per-request obs calls are the usual disabled-mode no-ops.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future

import numpy as np

from orp_tpu.guard.serve import GuardPolicy, Rejection, TransientDispatchError
from orp_tpu.obs import count as obs_count
from orp_tpu.obs import observe as obs_observe
from orp_tpu.obs import span
from orp_tpu.serve.metrics import ServingMetrics


@dataclasses.dataclass
class _Request:
    date_idx: int
    features: np.ndarray          # (rows, n_features)
    prices: np.ndarray | None     # (rows, k) or None
    future: Future
    submitted_at: float
    deadline: float | None = None  # absolute perf_counter instant; None = never


def _shed_order(req: _Request) -> tuple:
    """Watermark victim selection: earliest deadline first (the request
    most likely to expire unserved anyway), oldest submission as the
    tie-break / no-deadline fallback."""
    return (req.deadline if req.deadline is not None else float("inf"),
            req.submitted_at)


class MicroBatcher:
    """Queue + worker thread in front of a ``HedgeEngine``.

    ``max_batch`` caps coalesced rows per dispatch; ``max_wait_us`` caps how
    long the first request of a batch waits for company. Small waits trade
    single-request latency for device throughput — at 200µs a burst of
    single-row requests rides one executable instead of hundreds.

    ``policy`` (optional :class:`~orp_tpu.guard.GuardPolicy`) switches on
    deadlines, watermark shedding and transient-dispatch retries — see the
    module docstring. With a deadline in force, a future may resolve to a
    :class:`~orp_tpu.guard.Rejection` instead of ``(phi, psi, value)``;
    check ``guard.is_rejection(result)`` before unpacking.
    """

    def __init__(self, engine, *, max_batch: int = 1024,
                 max_wait_us: float = 200.0,
                 metrics: ServingMetrics | None = None,
                 policy: GuardPolicy | None = None):
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        self.engine = engine
        self.max_batch = int(max_batch)
        self.max_wait_us = float(max_wait_us)
        self.metrics = metrics
        self.policy = policy
        # one condition guards the deque + closed flag: submit needs to shed
        # arbitrary queued requests under the watermark policy, which a
        # SimpleQueue cannot express
        self._cv = threading.Condition()
        self._pending: collections.deque[_Request] = collections.deque()
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="orp-serve-batcher", daemon=True)
        self._worker.start()

    # -- client side ---------------------------------------------------------

    def submit(self, date_idx: int, states, prices=None, *,
               deadline_s: float | None = None) -> Future:
        """Enqueue one request; the Future resolves to ``(phi, psi, value)``
        for exactly these rows (``value`` None when ``prices`` is None) —
        or to a :class:`Rejection` when a guard policy shed it.

        ``deadline_s``: queue-age budget for THIS request (seconds from
        now), overriding the policy default. Ignored without a policy.
        """
        # promote scalars/rows to (rows, width) HERE: the worker indexes
        # .shape[0]/.shape[1] before any try block, so a lower-rank array
        # reaching it would kill the thread (and every pending future)
        feats = np.atleast_2d(np.asarray(states))
        pr = None if prices is None else np.atleast_2d(np.asarray(prices))
        fut: Future = Future()
        now = time.perf_counter()
        budget = deadline_s
        if budget is None and self.policy is not None:
            budget = (None if self.policy.deadline_ms is None
                      else self.policy.deadline_ms / 1e3)
        req = _Request(int(date_idx), feats, pr, fut, now,
                       None if (budget is None or self.policy is None)
                       else now + budget)
        shed: list[_Request] = []
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._pending.append(req)
            wm = None if self.policy is None else self.policy.queue_watermark
            while wm is not None and len(self._pending) > wm:
                # admission control: keep the queue at the watermark by
                # shedding the earliest-deadline request (possibly the one
                # just submitted) — a structured decision, not an error
                victim = min(self._pending, key=_shed_order)
                self._pending.remove(victim)
                shed.append(victim)
            self._cv.notify()
        for victim in shed:
            # resolved OUTSIDE the lock: set_result runs the future's
            # done-callbacks synchronously, and a callback that re-enters
            # the batcher (submit-on-reject is a natural client shape)
            # would deadlock on the held Condition
            self._shed(victim, "watermark")
        return fut

    def evaluate(self, date_idx: int, states, prices=None):
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(date_idx, states, prices).result()

    def close(self, timeout: float | None = 10.0) -> None:
        """Drain outstanding requests and stop the worker."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._worker.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- guard decisions -----------------------------------------------------

    def _shed(self, req: _Request, reason: str) -> None:
        """Resolve ``req`` with a structured Rejection + the shed signals."""
        queued = time.perf_counter() - req.submitted_at
        obs_count("guard/shed", reason=reason)
        obs_observe("serve/queue_age_seconds", queued, outcome="shed")
        if req.future.set_running_or_notify_cancel():
            req.future.set_result(Rejection(
                reason=reason, queued_s=queued,
                deadline_s=(None if req.deadline is None
                            else req.deadline - req.submitted_at)))

    # -- worker side ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            batch: list[_Request] = []
            expired: list[_Request] = []
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending:
                    return  # closed and drained
                rows = 0
                window_end = None  # opens at the first LIVE request
                while rows < self.max_batch:
                    if self._pending:
                        req = self._pending.popleft()
                        now = time.perf_counter()
                        if req.deadline is not None and now > req.deadline:
                            # expired while queued: never burn a device
                            # dispatch on an answer nobody is waiting for
                            expired.append(req)
                            continue
                        obs_observe("serve/queue_age_seconds",
                                    now - req.submitted_at, outcome="served")
                        batch.append(req)
                        rows += req.features.shape[0]
                        if window_end is None:
                            window_end = now + self.max_wait_us * 1e-6
                        continue
                    if not batch:
                        break  # everything popped had expired
                    remaining = window_end - time.perf_counter()
                    if self._closed or remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
            for req in expired:
                # outside the lock: resolving a future runs its
                # done-callbacks synchronously (see submit's shed note)
                self._shed(req, "deadline")
            if batch:
                self._dispatch(batch)

    def _dispatch_engine(self, date_idx: int, feats, pr):
        """One engine dispatch, with the policy's bounded retry-with-backoff
        for transient failures (a deterministic error propagates on attempt
        one — retrying it only repeats it with latency)."""
        pol = self.policy
        attempts = 1 + (pol.max_retries if pol is not None else 0)
        for attempt in range(1, attempts + 1):
            try:
                return self.engine.evaluate(date_idx, feats, pr)
            except TransientDispatchError:
                if attempt >= attempts:
                    raise
                obs_count("guard/retry", site="serve/dispatch",
                          attempt=str(attempt))
                # the worker sleeps through the backoff, so it is bounded
                # and small by policy (backoff_cap_ms)
                time.sleep(pol.backoff_s(attempt))

    def _dispatch(self, batch: list[_Request]) -> None:
        # group rows that can share one executable dispatch: same date, same
        # feature width and same prices shape-presence. Width in the key
        # means a malformed request (wrong feature count) fails on ITS OWN
        # future with the engine's error instead of poisoning the concat of
        # an entire well-formed batch.
        groups: dict[tuple, list[_Request]] = {}
        for req in batch:
            key = (req.date_idx, req.features.shape[1],
                   None if req.prices is None else req.prices.shape[1])
            groups.setdefault(key, []).append(req)
        for (date_idx, _, pwidth), reqs in groups.items():
            has_prices = pwidth is not None
            try:
                feats = np.concatenate([r.features for r in reqs], axis=0)
                pr = (np.concatenate([r.prices for r in reqs], axis=0)
                      if has_prices else None)
                obs_count("serve/batcher_dispatches")
                obs_count("serve/batcher_coalesced_requests", len(reqs))
                with span("serve/batch", attrs={"requests": len(reqs),
                                                "rows": int(feats.shape[0])}):
                    # no set_result: evaluate() blocks device-side internally,
                    # so the span is already device-complete
                    phi, psi, value = self._dispatch_engine(date_idx, feats, pr)
            except Exception as e:  # noqa: BLE001 — delivered per-future
                for r in reqs:
                    if not r.future.set_running_or_notify_cancel():
                        continue
                    r.future.set_exception(e)
                continue
            done = time.perf_counter()
            off = 0
            for r in reqs:
                n = r.features.shape[0]
                sl = (phi[off:off + n], psi[off:off + n],
                      value[off:off + n] if has_prices else None)
                off += n
                if r.future.set_running_or_notify_cancel():
                    r.future.set_result(sl)
                if self.metrics is not None:
                    self.metrics.record(done - r.submitted_at, n)
