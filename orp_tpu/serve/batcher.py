"""Continuous batching: keep the device busy while requests keep arriving.

The serving anti-pattern is one device dispatch per one-row request — launch
overhead dominates and the MXU runs at batch size 1. The first fix (PR 1)
was a synchronous micro-batcher: drain the queue, dispatch ONE bucketed
evaluation, block on it, repeat. That amortized dispatch but serialized the
host and the device: while the worker blocked on batch N, newly arrived
requests just aged in the queue (BENCH_serve.json before this tier: batcher
p99 19ms against an engine p99 of 0.68ms — the Python queue, not the
device, was the bottleneck).

This module is the production-inference shape instead — an async
CONTINUOUS-BATCHING dispatch loop riding JAX's async dispatch:

- **admit**    — drain everything pending into the largest batch that fits
  (``max_batch`` rows), grouped so rows that can share an executable ride
  one dispatch; requests that aged past their deadline are shed here,
  never dispatched.
- **dispatch** — submit the batch to the device WITHOUT blocking
  (``HedgeEngine.evaluate_async``): XLA's runtime owns it now.
- **overlap**  — while that batch executes, loop straight back to admit:
  requests that arrived in the meantime form the next batch, which is
  dispatched too (double-buffered — up to ``max_inflight`` batches queued
  on the device, so the device never waits on Python).
- **resolve**  — block on the OLDEST in-flight batch, slice each request's
  rows back out, and resolve every future in bulk OUTSIDE the lock (a
  done-callback that re-enters the batcher must never deadlock on the
  held Condition — the PR 6 lesson, generalized to the whole loop).

Correctness contract is unchanged from the synchronous batcher: every
request gets exactly the rows it submitted, in the order it submitted
them, bitwise-equal to a solo ``engine.evaluate`` of the same rows padded
into the same bucket family — the batcher changes latency/throughput,
never results. A failed dispatch propagates the exception to every future
in that group (not to unrelated groups).

Resilience (``orp_tpu/guard``, opt-in via a :class:`GuardPolicy`) keeps
its exact pre-async semantics under concurrency:

- every request's QUEUE AGE lands in ``serve/queue_age_seconds{outcome}``
  — the trace signal the shed decisions act on (the Dapper loop,
  PAPERS.md);
- per-request DEADLINES: a request whose queue age passes its deadline is
  shed with a structured :class:`Rejection` through its future
  (``guard/shed{reason="deadline"}``), never served late — so the queue
  age of every *served* request is bounded by its deadline, whatever a
  slow neighbour did;
- ADMISSION CONTROL: past ``queue_watermark`` pending ROWS (one unit on
  both lanes — a block's rows are backlog like anyone else's), the
  earliest-deadline (then oldest) request is shed at submit time
  (``guard/shed{reason="watermark"}``); an over-watermark block sheds its
  own TAIL rows as a slice instead;
- RETRIES of a dispatch that raised :class:`TransientDispatchError`, with
  bounded exponential backoff (``guard/retry``) — the backoff waits on an
  Event the close path sets, not ``time.sleep``, so it is interruptible
  and the dispatch loop never takes an unbreakable nap (lint rule
  ORP010's whole point).

Without a policy none of this runs; the per-request obs calls are the
usual disabled-mode no-ops.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
# distinct from builtin TimeoutError on Python <= 3.10, an alias after —
# raising THIS keeps every `except concurrent.futures.TimeoutError` a
# stdlib-Future client already wrote working against SlimFuture
from concurrent.futures import TimeoutError as _FutureTimeoutError

import numpy as np

from orp_tpu.guard.serve import GuardPolicy, Rejection, TransientDispatchError
from orp_tpu.obs import count as obs_count
from orp_tpu.obs import flight
from orp_tpu.obs import observe as obs_observe
from orp_tpu.obs import span
from orp_tpu.serve.ingest import (SHED_DEADLINE, SHED_WATERMARK, Block,
                                  as_deadline_column)
from orp_tpu.serve.metrics import ServingMetrics

_PENDING, _DONE, _FAILED = 0, 1, 2


class SlimFuture:
    """The per-request future, slimmed to what a serve tier needs.

    ``concurrent.futures.Future`` costs ~6µs to CONSTRUCT (a fresh
    Condition — two lock allocations — per instance) and ~1µs to resolve;
    at 10^5 requests/s that alone is more than half the Python budget.
    This class carries the used subset of the contract — ``result([
    timeout])``, ``exception()``, ``done()``, ``add_done_callback``,
    ``set_result``/``set_exception``, ``set_running_or_notify_cancel``
    (always True: a submitted request is never cancellable, its rows may
    already ride an in-flight dispatch) — over one CLASS-LEVEL lock and a
    lazily-allocated per-waiter Event, so the common open-loop shape
    (submit a stream, gather at the end, most futures already resolved)
    pays ~0.3µs per request.

    The shared lock is held only for state handoff (never while running
    callbacks or waiting), so resolutions on the dispatch-loop thread and
    waits on client threads contend for nanoseconds, not milliseconds.
    """

    __slots__ = ("_state", "_value", "_event", "_cbs")
    _lock = threading.Lock()  # class-level: state handoff only

    def __init__(self):
        self._state = _PENDING
        self._value = None
        self._event = None
        self._cbs = None

    def _resolve(self, state, value) -> None:
        with SlimFuture._lock:
            if self._state != _PENDING:
                raise RuntimeError("future already resolved")
            self._value = value
            self._state = state
            ev = self._event
            cbs = self._cbs
            self._cbs = None
        if ev is not None:
            ev.set()
        if cbs:
            for cb in cbs:
                cb(self)

    def set_result(self, value) -> None:
        self._resolve(_DONE, value)

    def set_exception(self, exc: BaseException) -> None:
        self._resolve(_FAILED, exc)

    def set_running_or_notify_cancel(self) -> bool:
        return True

    def done(self) -> bool:
        return self._state != _PENDING

    def add_done_callback(self, fn) -> None:
        run_now = False
        with SlimFuture._lock:
            if self._state != _PENDING:
                run_now = True
            elif self._cbs is None:
                self._cbs = [fn]
            else:
                self._cbs.append(fn)
        if run_now:
            fn(self)

    def _wait(self, timeout) -> None:
        with SlimFuture._lock:
            if self._state != _PENDING:
                return
            if self._event is None:
                self._event = threading.Event()
            ev = self._event
        if not ev.wait(timeout):
            raise _FutureTimeoutError("request not resolved within timeout")

    def result(self, timeout: float | None = None):
        if self._state == _PENDING:
            self._wait(timeout)
        if self._state == _FAILED:
            raise self._value
        return self._value

    def exception(self, timeout: float | None = None):
        if self._state == _PENDING:
            self._wait(timeout)
        return self._value if self._state == _FAILED else None


class _Request:
    __slots__ = ("date_idx", "features", "prices", "future", "submitted_at",
                 "deadline", "rows")

    def __init__(self, date_idx: int, features, prices, future: SlimFuture,
                 submitted_at: float, deadline: float | None):
        self.date_idx = date_idx
        self.features = features      # (rows, n_features)
        self.prices = prices          # (rows, k) or None
        self.future = future
        self.submitted_at = submitted_at
        self.deadline = deadline      # absolute perf_counter instant; None = never
        self.rows = features.shape[0]  # hoisted off the admit hot loop


@dataclasses.dataclass
class _Group:
    """One executable-sharing slice of an admitted batch: the requests whose
    concatenated rows ride one engine dispatch, plus that dispatch's outcome
    (a ``PendingEval``-shaped handle, or the exception that must be
    delivered to every future in the group at resolve time). The
    concatenated inputs are kept until resolution so a transient failure
    that only surfaces at BLOCK time can be re-dispatched under the same
    retry policy a dispatch-time failure gets."""

    reqs: list
    has_prices: bool
    rows: int
    date_idx: int = 0
    feats: object = None
    prices: object = None
    pending: object = None        # engine handle with .result()
    error: Exception | None = None
    # mixed-date lane (megakernel): per-row int32 date column when the
    # group spans dates — the block-time retry must re-dispatch through
    # the same fused path, so the column is kept alongside feats/prices
    dates: object = None
    # columnar lane: a LONE Block rides its OWN group (its rows are already
    # one contiguous device-shaped batch — zero concatenates clean-path) and
    # resolves through its single future with the per-row status column
    block: Block | None = None
    # cross-connection coalescing: SEVERAL blocks sharing an executable key
    # (same date, width, prices-presence) merge into ONE device dispatch —
    # many small connections of one tenant fill one launch (each tenant
    # owns its batcher, so the merge is per-tenant by construction) —
    # with per-origin live-row counts so each connection's reply columns
    # slice back out bitwise what its own dispatch would have served
    blocks: list | None = None
    block_lives: list | None = None


def _shed_order(req: _Request) -> tuple:
    """Watermark victim selection: earliest deadline first (the request
    most likely to expire unserved anyway), oldest submission as the
    tie-break / no-deadline fallback."""
    return (req.deadline if req.deadline is not None else float("inf"),
            req.submitted_at)


class MicroBatcher:
    """Async continuous-batching front of a ``HedgeEngine``.

    ``max_batch`` caps coalesced rows per dispatch; ``max_wait_us`` caps how
    long the first request of a batch waits for company WHEN THE DEVICE IS
    IDLE — once a batch is in flight, its execution time is the coalescing
    window (requests arriving meanwhile ride the next dispatch for free).
    ``max_inflight`` bounds how many dispatched batches may be queued on
    the device at once (2 = classic double buffering: one executing, one
    queued, the host free to admit a third).

    ``policy`` (optional :class:`~orp_tpu.guard.GuardPolicy`) switches on
    deadlines, watermark shedding and transient-dispatch retries — see the
    module docstring. With a deadline in force, a future may resolve to a
    :class:`~orp_tpu.guard.Rejection` instead of ``(phi, psi, value)``;
    check ``guard.is_rejection(result)`` before unpacking.

    ``ragged=True`` (optionally with a shared ``planner``) turns on
    pad-waste-aware dispatch planning (:mod:`orp_tpu.serve.ragged`);
    ``mixed_dates=True`` fuses requests at different rebalance dates into
    one megakernel dispatch (:mod:`orp_tpu.serve.megakernel`). Both are
    opt-in: default-off keeps the per-date always-merge dispatch shape
    existing tests and benches pin.
    """

    def __init__(self, engine, *, max_batch: int = 1024,
                 max_wait_us: float = 200.0,
                 metrics: ServingMetrics | None = None,
                 policy: GuardPolicy | None = None,
                 max_inflight: int = 2,
                 min_fill: int | None = None,
                 coalesce_blocks: bool = True,
                 ragged: bool = False,
                 planner=None,
                 mixed_dates: bool = False):
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch} must be >= 1")
        if max_inflight < 1:
            raise ValueError(f"max_inflight={max_inflight} must be >= 1")
        self.engine = engine
        self.max_batch = int(max_batch)
        self.max_wait_us = float(max_wait_us)
        self.max_inflight = int(max_inflight)
        # busy-device admission threshold: while a batch is in flight,
        # don't dispatch another until this many requests are pending —
        # resolving the in-flight batch first lets arrivals accumulate into
        # a fuller bucket (each dispatch has a fixed launch cost; under
        # sustained load eager tiny batches burn it per handful of rows).
        # Never delays an idle device: with nothing in flight the
        # max_wait_us window is the only batching wait.
        self.min_fill = (max(1, self.max_batch // 8) if min_fill is None
                         else int(min_fill))
        # cross-connection block coalescing: admitted blocks sharing one
        # executable key (date, width, prices-presence) concatenate into ONE
        # device dispatch — many small connections of one tenant fill one
        # launch instead of paying one per connection (each tenant owns its
        # batcher, so the merge is per-tenant by construction). Per-origin
        # row-slice bookkeeping makes each block's reply bitwise what its
        # own dispatch serves (the forward is per-row); `False` keeps the
        # one-block-one-dispatch shape (the A/B the fleet bench pins bits
        # against).
        self.coalesce_blocks = bool(coalesce_blocks)
        # ragged batching (serve/ragged.py), opt-in: a pad-waste-aware
        # BucketPlanner partitions coalesced blocks into dispatch groups
        # (merge vs keep-separate) and shatters an over-padded batch into
        # exact-bucket chunks when its cost model says the extra launches
        # undercut the padding. `False` keeps the always-merge pow2 shape
        # (the A/B the ragged bench phase pins against). Pass `planner`
        # to share a profile-fed instance; `ragged=True` alone builds a
        # proxy-cost default.
        self.planner = planner
        if ragged and self.planner is None:
            from orp_tpu.serve.ragged import BucketPlanner

            self.planner = BucketPlanner()
        # mixed-date lane (serve/megakernel.py), opt-in: per-request
        # admission stops keying groups on date_idx — rows at DIFFERENT
        # rebalance dates concatenate into one fused megakernel dispatch
        # (engine.evaluate_mixed_async) instead of one launch per date.
        # Default False: the per-date grouping is the shape the existing
        # dispatch-count pins (tests/test_serve.py) are written against,
        # and the fused path needs a single-device engine.
        self.mixed_dates = bool(mixed_dates)
        self.metrics = metrics
        self.policy = policy
        # stuck-dispatch watchdog (serve/health.py), opt-in via the policy's
        # hard_wall_ms: bounds the resolve-stage block and feeds the
        # engine's circuit breaker on a trip; absent -> zero cost
        self._watchdog = None
        if policy is not None and policy.hard_wall_ms is not None:
            from orp_tpu.serve.health import DispatchWatchdog

            self._watchdog = DispatchWatchdog(
                policy.hard_wall_ms,
                on_trip=getattr(engine, "watchdog_trip", None),
                on_ok=getattr(engine, "watchdog_ok", None),
            )
        # one condition guards the deque + closed flag: submit needs to shed
        # arbitrary queued requests under the watermark policy, which a
        # SimpleQueue cannot express
        self._cv = threading.Condition()
        self._pending: collections.deque = collections.deque()
        # row count of everything queued (requests AND blocks): the columnar
        # lane's watermark unit — shedding whole blocks by request count
        # would make a 1024-row block as cheap as a 1-row request
        self._pending_rows = 0
        self._closed = False
        # set at close(): wakes a retry backoff immediately instead of
        # letting the dispatch loop finish a nap nobody is waiting for
        self._interrupt = threading.Event()
        self._worker = threading.Thread(
            target=self._run, name="orp-serve-batcher", daemon=True)
        self._worker.start()

    # -- client side ---------------------------------------------------------

    def submit(self, date_idx: int, states, prices=None, *,
               deadline_s: float | None = None) -> SlimFuture:
        """Enqueue one request; the future resolves to ``(phi, psi, value)``
        for exactly these rows (``value`` None when ``prices`` is None) —
        or to a :class:`Rejection` when a guard policy shed it.

        ``deadline_s``: queue-age budget for THIS request (seconds from
        now), overriding the policy default. Ignored without a policy.
        """
        # promote scalars/rows to (rows, width) HERE: the worker indexes
        # .shape[0]/.shape[1] before any try block, so a lower-rank array
        # reaching it would kill the thread (and every pending future)
        feats = np.atleast_2d(np.asarray(states))
        pr = None if prices is None else np.atleast_2d(np.asarray(prices))
        fut = SlimFuture()
        now = time.perf_counter()
        budget = deadline_s
        if budget is None and self.policy is not None:
            budget = (None if self.policy.deadline_ms is None
                      else self.policy.deadline_ms / 1e3)
        req = _Request(int(date_idx), feats, pr, fut, now,
                       None if (budget is None or self.policy is None)
                       else now + budget)
        shed: list[_Request] = []
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._pending.append(req)
            self._pending_rows += req.rows
            wm = None if self.policy is None else self.policy.queue_watermark
            # the watermark is ROW-counted on both lanes (one unit, one
            # meaning — a 1024-row block is 1024 rows of backlog, not one
            # entry): keep the queued rows at the watermark by shedding the
            # earliest-deadline request (possibly the one just submitted) —
            # a structured decision, not an error. Queued BLOCKS are not
            # victims: the columnar lane sheds by tail-slice at its own
            # admission edge (submit_block), never by growing a per-row
            # Rejection out of a queued column
            while wm is not None and self._pending_rows > wm:
                victim = min(
                    (r for r in self._pending if not isinstance(r, Block)),
                    key=_shed_order, default=None)
                if victim is None:
                    break
                self._pending.remove(victim)
                self._pending_rows -= victim.rows
                shed.append(victim)
            if len(self._pending) == 1:
                # notify only on the empty->nonempty edge: a worker in its
                # coalescing window picks up company at the window end
                # anyway, and per-submit notifies are measurable at 10^5/s
                self._cv.notify()
        for victim in shed:
            # resolved OUTSIDE the lock: set_result runs the future's
            # done-callbacks synchronously, and a callback that re-enters
            # the batcher (submit-on-reject is a natural client shape)
            # would deadlock on the held Condition
            self._shed(victim, "watermark")
        return fut

    def submit_block(self, date_idx: int, states, prices=None,
                     deadlines=None, *, trace=None) -> SlimFuture:
        """Columnar ingest lane: admit N rows for ONE date under one lock
        pass with ONE future for the whole block. The future resolves to a
        :class:`~orp_tpu.serve.ingest.BlockResult` — contiguous ``phi``/
        ``psi``/``value`` columns plus a per-row ``status`` column — whose
        served rows are BITWISE what N per-request ``submit`` calls of the
        same rows return (the forward is per-row; only the Python admission
        cost changes).

        ``states``: ``(n, n_features)`` feature rows (C-contiguous is the
        zero-copy path). ``prices``: optional ``(n, k)``. ``deadlines``:
        per-row queue-age budgets in seconds — an ``(n,)`` column, a scalar
        for every row, or None for the policy default. Like the per-request
        lane, deadlines/watermark only act under a :class:`GuardPolicy`;
        guard decisions come back through the STATUS column (deadline
        expiry = one mask compare at admit; watermark = the tail rows past
        the row-counted watermark shed as a slice at submit), never as
        per-row ``Rejection`` objects.

        ``trace``: an optional ``(trace_id, parent_span)`` distributed-trace
        context (``obs.new_trace()`` / a decoded frame's stamp). A traced
        block's admit/dispatch/device instants become ``trace/queue`` /
        ``trace/dispatch`` / ``trace/resolve`` span events under that
        trace_id, and its :class:`~orp_tpu.serve.ingest.BlockResult` carries
        the ``(queue_age_s, dispatch_s)`` server-timing pair. ``None`` (the
        default) costs one ``is not None`` test per block — the zero-cost
        discipline, block-amortized.
        """
        feats = np.atleast_2d(np.ascontiguousarray(states))
        n = feats.shape[0]
        if n < 1 or feats.ndim != 2:
            raise ValueError(
                f"block of shape {np.shape(states)}: submit_block takes a "
                "non-empty (rows, n_features) feature matrix")
        pr = None
        if prices is not None:
            pr = np.atleast_2d(np.ascontiguousarray(prices))
            if pr.shape[0] != n:
                raise ValueError(
                    f"prices column has {pr.shape[0]} rows, features {n} — "
                    "a block is one row set")
        now = time.perf_counter()
        dl = None
        if self.policy is not None:
            default = (None if self.policy.deadline_ms is None
                       else self.policy.deadline_ms / 1e3)
            dl = as_deadline_column(deadlines, n, now, default)
        blk = Block(int(date_idx), feats, pr, SlimFuture(), now, dl,
                    trace=trace)
        n_wm = 0
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            wm = None if self.policy is None else self.policy.queue_watermark
            if wm is not None and self._pending_rows + n > wm:
                # row-counted admission control, vectorized: strike the TAIL
                # rows past the watermark in one slice — never grow per-row
                # objects out of an overload decision
                n_wm = blk.shed_tail(max(0, wm - self._pending_rows),
                                     SHED_WATERMARK)
            live = blk.n_live
            if live:
                self._pending.append(blk)
                self._pending_rows += live
                if len(self._pending) == 1:
                    self._cv.notify()
        # signals + resolution OUTSIDE the lock (the per-request shed rule)
        blk.emit_shed(SHED_WATERMARK, n_wm)
        if not live:
            blk.resolve_shed_only()
        return blk.future

    def evaluate(self, date_idx: int, states, prices=None):
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(date_idx, states, prices).result()

    def close(self, timeout: float | None = 10.0) -> None:
        """Drain outstanding requests and stop the worker."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._interrupt.set()
            self._cv.notify_all()
        self._worker.join(timeout)
        if self._watchdog is not None:
            self._watchdog.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- guard decisions -----------------------------------------------------

    def _shed(self, req: _Request, reason: str) -> None:
        """Resolve ``req`` with a structured Rejection + the shed signals."""
        queued = time.perf_counter() - req.submitted_at
        obs_count("guard/shed", reason=reason)
        obs_observe("serve/queue_age_seconds", queued, outcome="shed")
        flight.record("shed", reason=reason, queued_s=round(queued, 6))
        if req.future.set_running_or_notify_cancel():
            req.future.set_result(Rejection(
                reason=reason, queued_s=queued,
                deadline_s=(None if req.deadline is None
                            else req.deadline - req.submitted_at)))

    # -- dispatch loop -------------------------------------------------------
    #
    # admit -> dispatch -> (overlap) -> resolve. The loop never blocks on a
    # device result while there is admission or dispatch work to do, and it
    # never resolves futures under the Condition. ORP010 lints the admit/
    # dispatch stages for blocking calls; _resolve is the one stage whose
    # JOB is to block.

    def _run(self) -> None:
        inflight: collections.deque[list[_Group]] = collections.deque()
        while True:
            # only block waiting for work when the device has none either —
            # with a batch in flight its execution is the natural window
            batch, expired, closed = self._admit(block=not inflight)
            for req in expired:
                # outside the lock: resolving a future runs its
                # done-callbacks synchronously (see submit's shed note)
                if isinstance(req, Block):
                    # a block every row of which expired: its shed signals
                    # were emitted at admit, only the resolution is left
                    req.resolve_shed_only()
                else:
                    self._shed(req, "deadline")
            if batch:
                inflight.append(self._dispatch(batch))
            if inflight and (not batch or len(inflight) >= self.max_inflight):
                # oldest batch first: FIFO resolution preserves the
                # submission-order contract per request
                self._resolve(inflight.popleft())
                continue
            if closed and not batch and not inflight:
                return

    def _admit(self, block: bool):
        """Drain pending requests into the largest batch that fits
        (``max_batch`` rows): returns ``(batch, expired, closed)``. With
        ``block=True`` waits for the first live request and then holds the
        ``max_wait_us`` coalescing window open for company; with
        ``block=False`` (a batch is already executing) takes whatever is
        there RIGHT NOW and returns — continuous batching's admission
        rule."""
        batch: list[_Request] = []
        expired: list[_Request] = []
        with self._cv:
            if block:
                while not self._pending and not self._closed:
                    self._cv.wait()
            elif len(self._pending) < self.min_fill and not self._closed:
                # device busy + thin queue: let the resolve of the
                # in-flight batch be the wait that fills this one
                return batch, expired, False
            rows = 0
            window_end = None  # opens at the first LIVE request
            while rows < self.max_batch:
                if self._pending:
                    req = self._pending.popleft()
                    now = time.perf_counter()
                    if isinstance(req, Block):
                        # columnar lane: deadline expiry is ONE mask
                        # compare against the float64 deadline column —
                        # expired rows are struck in place, never objects
                        self._pending_rows -= req.n_live
                        n_exp = req.mask_expired(now)
                        req.emit_shed(SHED_DEADLINE, n_exp)
                        live = req.n_live
                        if not live:
                            expired.append(req)
                            continue
                        obs_observe("serve/queue_age_seconds",
                                    now - req.submitted_at, outcome="served")
                        if req.trace is not None:
                            # the queue segment ends here; `now` was read
                            # anyway, so a traced block costs one store
                            req.t_admit = now
                        batch.append(req)
                        rows += live
                        if window_end is None:
                            window_end = now + self.max_wait_us * 1e-6
                        continue
                    self._pending_rows -= req.rows
                    if req.deadline is not None and now > req.deadline:
                        # expired while queued: never burn a device
                        # dispatch on an answer nobody is waiting for
                        expired.append(req)
                        continue
                    obs_observe("serve/queue_age_seconds",
                                now - req.submitted_at, outcome="served")
                    batch.append(req)
                    rows += req.rows
                    if window_end is None:
                        window_end = now + self.max_wait_us * 1e-6
                    continue
                if not batch or not block:
                    break
                remaining = window_end - time.perf_counter()
                if self._closed or remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            return batch, expired, self._closed

    def _dispatch(self, batch: list[_Request]) -> list[_Group]:
        """Group the admitted batch by executable compatibility and submit
        each group to the device WITHOUT blocking. Returns the in-flight
        groups; exceptions are captured per group and delivered at resolve
        time (outside any lock, never poisoning unrelated groups).

        Grouping key: same date, same feature width and same prices
        shape-presence. Width in the key means a malformed request (wrong
        feature count) fails on ITS OWN future with the engine's error
        instead of poisoning the concat of an entire well-formed batch.

        A LONE :class:`~orp_tpu.serve.ingest.Block` rides its OWN group:
        its rows are already one contiguous device-shaped batch (the whole
        point of the columnar lane — zero concatenates on the clean path),
        and its single future resolves with the status column instead of
        per-request slices. SEVERAL admitted blocks sharing one key — the
        fleet's many-small-connections-per-tenant shape — coalesce into
        ONE dispatch (``coalesce_blocks``) with per-origin live-row
        slices, so each connection still gets bitwise its own dispatch's
        columns (per-row forward; pinned in tests/test_fleet.py)."""
        groups: dict[tuple, list[_Request]] = {}
        block_groups: dict[tuple, list[Block]] = {}
        out: list[_Group] = []
        for req in batch:
            if isinstance(req, Block):
                key = (req.date_idx, req.features.shape[1],
                       None if req.prices is None else req.prices.shape[1])
                block_groups.setdefault(key, []).append(req)
                continue
            # mixed-date lane: drop the date from the key — requests at
            # different rebalance dates fuse into one megakernel dispatch
            key = ((None if self.mixed_dates else req.date_idx),
                   req.features.shape[1],
                   None if req.prices is None else req.prices.shape[1])
            groups.setdefault(key, []).append(req)
        for (date_idx, _, pwidth), blks in block_groups.items():
            if (len(blks) > 1 and self.coalesce_blocks
                    and self.planner is not None):
                # ragged: the planner's DP picks merge vs keep-separate
                # per run of admitted blocks instead of always-merge; the
                # groups are consecutive in admission order, so every
                # origin's reply still slices out contiguously
                parts = self.planner.plan([b.n_live for b in blks])
                if len(parts) > 1:
                    obs_count("serve/ragged_plans")
                for lo, hi in parts:
                    part = blks[lo:hi]
                    if len(part) == 1:
                        out.append(self._dispatch_block(part[0]))
                    else:
                        out.append(self._dispatch_coalesced(
                            date_idx, pwidth, part))
                continue
            if len(blks) == 1 or not self.coalesce_blocks:
                for blk in blks:
                    out.append(self._dispatch_block(blk))
                continue
            out.append(self._dispatch_coalesced(date_idx, pwidth, blks))
        for (date_idx, _, pwidth), reqs in groups.items():
            has_prices = pwidth is not None
            g = _Group(reqs=reqs, has_prices=has_prices,
                       rows=sum(r.features.shape[0] for r in reqs),
                       date_idx=(reqs[0].date_idx if date_idx is None
                                 else date_idx))
            out.append(g)
            try:
                g.feats = np.concatenate([r.features for r in reqs], axis=0)
                g.prices = (np.concatenate([r.prices for r in reqs], axis=0)
                            if has_prices else None)
                if (date_idx is None
                        and len({r.date_idx for r in reqs}) > 1):
                    # genuinely mixed dates: one fused megakernel dispatch
                    # instead of one launch per distinct date
                    g.dates = np.concatenate(
                        [np.full(r.rows, r.date_idx, np.int32)
                         for r in reqs])
                    g.pending = self._dispatch_engine(
                        g.date_idx, g.feats, g.prices, dates=g.dates)
                else:
                    g.pending = self._dispatch_planned(g.date_idx, g.feats,
                                                       g.prices)
            except Exception as e:  # orp: noqa[ORP009] -- delivered to every future in the group by _resolve
                g.error = e
                continue
            # counters record AFTER the dispatch succeeds: a group whose
            # retries exhaust must not inflate the device-traffic telemetry
            obs_count("serve/batcher_dispatches")
            obs_count("serve/batcher_coalesced_requests", len(reqs))
            if self.metrics is not None:
                cap = (self.engine.bucket_for(g.rows)
                       if hasattr(self.engine, "bucket_for") else
                       self.max_batch)
                self.metrics.record_dispatch(len(reqs), g.rows, cap)
        return out

    def _dispatch_block(self, blk: Block) -> _Group:
        """One block, one dispatch — the PR 10 lane unchanged: the block's
        own contiguous columns go to the device with zero concatenates."""
        feats, prices = blk.live_columns()
        g = _Group(reqs=[], has_prices=prices is not None,
                   rows=int(feats.shape[0]), date_idx=blk.date_idx,
                   block=blk)
        try:
            g.feats, g.prices = feats, prices
            g.pending = self._dispatch_planned(g.date_idx, feats, prices)
        except Exception as e:  # orp: noqa[ORP009] -- delivered to the block's future by _resolve
            g.error = e
            return g
        if blk.trace is not None:
            # the dispatch segment ends at device submission
            blk.t_dispatch = time.perf_counter()
        obs_count("serve/batcher_dispatches")
        obs_count("serve/ingest_block_rows", g.rows, sink_event=False)
        if self.metrics is not None:
            cap = (self.engine.bucket_for(g.rows)
                   if hasattr(self.engine, "bucket_for") else
                   self.max_batch)
            self.metrics.record_dispatch(1, g.rows, cap)
        return g

    def _dispatch_coalesced(self, date_idx: int, pwidth, blks) -> _Group:
        """Cross-connection coalescing: N admitted blocks with one
        executable key ride ONE device dispatch. The concatenation order is
        admission order, and each block's live-row count is kept so the
        resolve stage slices every origin's columns back out — bitwise what
        a per-block dispatch serves (the forward is per-row, and bucket
        padding rides OUTSIDE the sliced rows)."""
        has_prices = pwidth is not None
        lives = []
        feat_cols = []
        price_cols = [] if has_prices else None
        for blk in blks:
            f, p = blk.live_columns()
            lives.append(int(f.shape[0]))
            feat_cols.append(f)
            if has_prices:
                price_cols.append(p)
        g = _Group(reqs=[], has_prices=has_prices, rows=sum(lives),
                   date_idx=date_idx, blocks=list(blks), block_lives=lives)
        try:
            g.feats = np.concatenate(feat_cols, axis=0)
            g.prices = (np.concatenate(price_cols, axis=0)
                        if has_prices else None)
            g.pending = self._dispatch_planned(date_idx, g.feats, g.prices)
        except Exception as e:  # orp: noqa[ORP009] -- delivered to every block future by _resolve
            g.error = e
            return g
        now = time.perf_counter()
        for blk in blks:
            if blk.trace is not None:
                blk.t_dispatch = now
        obs_count("serve/batcher_dispatches")
        obs_count("serve/batcher_coalesced_blocks", len(blks))
        obs_count("serve/ingest_block_rows", g.rows, sink_event=False)
        if self.metrics is not None:
            cap = (self.engine.bucket_for(g.rows)
                   if hasattr(self.engine, "bucket_for") else
                   self.max_batch)
            self.metrics.record_dispatch(len(blks), g.rows, cap)
        return g

    def _dispatch_engine(self, date_idx: int, feats, pr, dates=None):
        """One non-blocking engine dispatch, with the policy's bounded
        retry-with-backoff for transient failures (a deterministic error
        propagates on attempt one — retrying it only repeats it with
        latency). The backoff waits on the close-interrupt Event, not
        ``time.sleep``: bounded, small by policy, and breakable.
        ``dates`` (per-row int32 column) routes through the fused
        mixed-date megakernel lane instead of the single-date bucket."""
        if dates is not None:
            submit = lambda d, f, p: self.engine.evaluate_mixed_async(
                dates, f, p)
        else:
            submit = getattr(self.engine, "evaluate_async", None)
            if submit is None:
                # a plain-evaluate engine still works behind the batcher:
                # its blocking result is wrapped to look already-resolved
                submit = lambda d, f, p: _Resolved(
                    self.engine.evaluate(d, f, p))
        pol = self.policy
        attempts = 1 + (pol.max_retries if pol is not None else 0)
        for attempt in range(1, attempts + 1):
            try:
                return submit(date_idx, feats, pr)
            except TransientDispatchError:
                if attempt >= attempts:
                    raise
                obs_count("guard/retry", site="serve/dispatch",
                          attempt=str(attempt))
                self._interrupt.wait(pol.backoff_s(attempt))

    def _dispatch_planned(self, date_idx: int, feats, pr):
        """Engine dispatch with the ragged planner's split decision
        applied: an over-padded batch shatters into exact-bucket chunks
        (each its own engine dispatch; XLA queues them back to back) and
        resolves through one concatenating handle. Without a planner —
        or when its cost model keeps the batch whole — this IS
        ``_dispatch_engine``."""
        if self.planner is not None:
            chunks = self.planner.split_rows(int(feats.shape[0]))
            if chunks is not None:
                obs_count("serve/ragged_splits")
                pends, off = [], 0
                for c in chunks:
                    pends.append(self._dispatch_engine(
                        date_idx, feats[off:off + c],
                        None if pr is None else pr[off:off + c]))
                    off += c
                return _SplitPending(pends)
        return self._dispatch_engine(date_idx, feats, pr)

    def _blocked(self, pending):
        """The ONE block point on a dispatched batch: straight through
        without a watchdog, hard-wall-bounded with one (a hang past
        ``hard_wall_ms`` force-fails as a ``WatchdogTrip`` — transient, so
        the block-time retry below applies; the trip already fed the
        engine's breaker, which may have demoted the hanging bucket)."""
        if self._watchdog is not None:
            return self._watchdog.block(
                pending.result, tag=getattr(pending, "bucket", None))
        return pending.result()

    def _blocked_result(self, g: _Group):
        """Block on ``g``'s dispatched evaluation. A transient failure that
        only SURFACES here (XLA's async runtime raises at block time, not
        submission — or the watchdog force-failed a hung batch) gets the
        same bounded retry policy a dispatch-time failure got: the whole
        group re-dispatches through ``_dispatch_engine`` (whose own retry
        loop then applies). Without a retrying policy the error propagates
        as before — retrying is the operator's call, never a silent
        default."""
        try:
            return self._blocked(g.pending)
        except TransientDispatchError:
            pol = self.policy
            if pol is None or pol.max_retries < 1:
                raise
            obs_count("guard/retry", site="serve/block", attempt="1")
            self._interrupt.wait(pol.backoff_s(1))
            return self._blocked(
                self._dispatch_engine(g.date_idx, g.feats, g.prices,
                                      dates=g.dates))

    def _resolve(self, groups: list[_Group]) -> None:
        """Block on the oldest in-flight batch and resolve every future in
        bulk — strictly outside the Condition (done-callbacks run
        synchronously and may re-enter the batcher)."""
        for g in groups:
            if g.block is not None:
                self._resolve_block(g)
                continue
            if g.blocks is not None:
                self._resolve_coalesced(g)
                continue
            if g.error is not None:
                for r in g.reqs:
                    if r.future.set_running_or_notify_cancel():
                        r.future.set_exception(g.error)
                continue
            try:
                with span("serve/batch", attrs={"requests": len(g.reqs),
                                                "rows": g.rows}) as sp:
                    # result() blocks device-side internally, so the span
                    # is already device-complete
                    phi, psi, value = self._blocked_result(g)
            except Exception as e:  # noqa: BLE001 — delivered per-future
                for r in g.reqs:
                    if r.future.set_running_or_notify_cancel():
                        r.future.set_exception(e)
                continue
            done = time.perf_counter()
            off = 0
            served = []
            for r in g.reqs:
                n = r.features.shape[0]
                sl = (phi[off:off + n], psi[off:off + n],
                      value[off:off + n] if g.has_prices else None)
                off += n
                if r.future.set_running_or_notify_cancel():
                    r.future.set_result(sl)
                served.append((done - r.submitted_at, n))
            if self.metrics is not None:
                self.metrics.record_many(served)

    def _resolve_block(self, g: _Group) -> None:
        """Resolve a columnar block's single future: the dispatched live
        rows scatter back into full-size columns next to the status ledger
        (``ingest.Block.resolve_served``); a failed dispatch delivers its
        exception to the one future — no per-row error objects either."""
        blk = g.block
        if g.error is not None:
            if blk.future.set_running_or_notify_cancel():
                blk.future.set_exception(g.error)
            return
        try:
            with span("serve/batch", attrs={"requests": 1,
                                            "rows": g.rows}) as sp:
                phi, psi, value = self._blocked_result(g)
        except Exception as e:  # noqa: BLE001 — delivered through the block future
            if blk.future.set_running_or_notify_cancel():
                blk.future.set_exception(e)
            return
        done = time.perf_counter()
        timing = blk.trace_report(done) if blk.trace is not None else None
        blk.resolve_served(phi, psi, value, timing=timing)
        if self.metrics is not None:
            self.metrics.record(done - blk.submitted_at, g.rows)

    def _resolve_coalesced(self, g: _Group) -> None:
        """Resolve a coalesced multi-block dispatch: slice each origin's
        live rows back out of the shared columns — contiguous slices in
        admission order, so every connection's reply is bitwise its own
        dispatch's — and resolve each block's future independently (one
        dispatch failure reaches every coalesced future; there is one
        device answer to miss)."""
        if g.error is not None:
            for blk in g.blocks:
                if blk.future.set_running_or_notify_cancel():
                    blk.future.set_exception(g.error)
            return
        try:
            with span("serve/batch", attrs={"requests": len(g.blocks),
                                            "rows": g.rows}) as sp:
                phi, psi, value = self._blocked_result(g)
        except Exception as e:  # noqa: BLE001 — delivered through every block future
            for blk in g.blocks:
                if blk.future.set_running_or_notify_cancel():
                    blk.future.set_exception(e)
            return
        done = time.perf_counter()
        off = 0
        served = []
        for blk, n_live in zip(g.blocks, g.block_lives):
            sl_phi = phi[off:off + n_live]
            sl_psi = psi[off:off + n_live]
            sl_val = value[off:off + n_live] if g.has_prices else None
            off += n_live
            timing = blk.trace_report(done) if blk.trace is not None else None
            blk.resolve_served(sl_phi, sl_psi, sl_val, timing=timing)
            served.append((done - blk.submitted_at, n_live))
        if self.metrics is not None:
            self.metrics.record_many(served)


class _Resolved:
    """Adapter: a blocking engine's already-materialized result wearing the
    ``PendingEval`` interface, so the dispatch loop has one resolve path."""

    __slots__ = ("_out",)

    def __init__(self, out):
        self._out = out

    def result(self):
        return self._out


class _SplitPending:
    """A ragged split's in-flight chunks wearing ONE ``PendingEval``
    interface: ``result()`` blocks each chunk in dispatch order and
    concatenates the unpadded rows back — bitwise the unsplit dispatch's
    rows (the forward is per-row and XLA row results are batch-size
    invariant; the ragged bench phase pins it). Every existing resolve
    path then works unchanged on a split group."""

    __slots__ = ("_pends",)

    def __init__(self, pends):
        self._pends = pends

    def result(self):
        outs = [p.result() for p in self._pends]
        phi = np.concatenate([o[0] for o in outs], axis=0)
        psi = np.concatenate([o[1] for o in outs], axis=0)
        value = (np.concatenate([o[2] for o in outs], axis=0)
                 if outs[0][2] is not None else None)
        return phi, psi, value
