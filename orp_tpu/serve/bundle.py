"""Hedge-policy bundles: export a trained walk to disk, load it back verified.

A *bundle* is the deployable unit the training pipelines produce: the per-date
MLP params (``BackwardResult.policy_state()`` — params + tiny fit metrics,
never the O(paths x dates) training ledgers), the model architecture, the
value/holdings combine semantics, and the evaluation metadata (rebalance-knot
times, report scale). Layout::

    <dir>/bundle.json           architecture + combine semantics + metadata
    <dir>/run_fingerprint.txt   compatibility guard (utils/fingerprint.py)
    <dir>/policy/0/...          orbax pytree of policy_state()

Loading verifies twice: the fingerprint side file must match the string
recomputed from ``bundle.json`` (catches a hand-edited or mixed directory),
and the restored params must have exactly the shapes the recorded
architecture implies (``verify_policy_compat`` — catches a ``policy/``
subtree swapped in from another bundle). A loaded ``PolicyBundle`` exposes
the same fields the ``*_oos`` pipelines read off a ``PipelineResult``
(``backward``/``dual_mode``/``holdings_combine``/``cost_of_capital``/
``sim_seed``), so it drops into out-of-sample evaluation and the serving
engine interchangeably with an in-memory result.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np

from orp_tpu.models.mlp import HedgeMLP
from orp_tpu.train.backward import BackwardResult
from orp_tpu.utils.atomic import atomic_write_text
from orp_tpu.utils.checkpoint import latest_step, load_checkpoint, save_checkpoint
from orp_tpu.utils.fingerprint import (
    policy_fingerprint,
    verify_fingerprint,
    verify_policy_compat,
    write_fingerprint,
)

# v2 (guard round): the policy step under policy/ carries a per-step
# integrity digest side file that the loader now VERIFIES — a digest-less
# v1 bundle would refuse deep inside the checkpoint layer with a
# resume-worded error, so the format gate refuses it up front instead
# (clean message: re-export with the current code)
_FORMAT = "orp-bundle-v2"
_META = "bundle.json"
_POLICY_SUBDIR = "policy"

# the model dtype is serialized by name; only dtypes the models actually
# support are representable (an unknown name fails the load loudly)
_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float64": jnp.float64}  # orp: noqa[ORP001] -- serialization table must name every loadable dtype


@dataclasses.dataclass
class PolicyBundle:
    """A deployable hedge policy: what ``*_oos`` and the serving engine need,
    nothing path-simulation-specific."""

    model: HedgeMLP
    backward: BackwardResult      # params-only (ledger fields are None)
    times: np.ndarray             # rebalance-knot times (n_dates+1,)
    adjustment_factor: float      # report scale (S0 / strike / N0*premium)
    dual_mode: str
    holdings_combine: str
    cost_of_capital: float
    sim_seed: int | None          # training path seed — *_oos refuses replaying it
    fingerprint: str
    aot_dir: pathlib.Path | None = None  # bundle dir holding serialized
    # serving executables (orp export --aot → <dir>/aot/); the engine
    # deserializes them at construction (orp_tpu/aot/bundle_exec.py)
    # model-health baseline (orp_tpu/obs/quality.py), baked at export:
    # per-feature training-feature sketch (the serve-time drift monitor's
    # reference), the pinned validation scenario set (the quality canary
    # gate's scenario source) and the training-time hedge-error level.
    # None on pre-quality bundles — everything downstream degrades
    # gracefully (no drift monitor, quality gate refuses in flag-speak)
    feature_sketch: object | None = None       # obs.quality.FeatureSketch
    validation: object | None = None           # obs.quality.ValidationSpec
    hedge_error_baseline: float | None = None  # normalised units

    @property
    def n_dates(self) -> int:
        return len(self.times) - 1


def _model_meta(model: HedgeMLP) -> dict:
    return {
        "n_features": model.n_features,
        "hidden": list(model.hidden),
        "negative_slope": model.negative_slope,
        "constrain_self_financing": model.constrain_self_financing,
        "init_scale": model.init_scale,
        "dtype": jnp.dtype(model.dtype).name,
        "n_hedge_assets": model.n_hedge_assets,
    }


def _model_from_meta(meta: dict) -> HedgeMLP:
    dtype_name = meta["dtype"]
    if dtype_name not in _DTYPES:
        raise ValueError(
            f"bundle records unsupported model dtype {dtype_name!r} "
            f"(known: {sorted(_DTYPES)})"
        )
    return HedgeMLP(
        n_features=int(meta["n_features"]),
        hidden=tuple(int(h) for h in meta["hidden"]),
        negative_slope=float(meta["negative_slope"]),
        constrain_self_financing=bool(meta["constrain_self_financing"]),
        init_scale=float(meta["init_scale"]),
        dtype=_DTYPES[dtype_name],
        n_hedge_assets=int(meta["n_hedge_assets"]),
    )


def export_bundle(result, directory: str | pathlib.Path, *,
                  store=None, tenant: str | None = None) -> PolicyBundle:
    """Export a trained ``PipelineResult`` as a policy bundle under
    ``directory`` (created; must not already hold a different bundle).

    ``result`` must carry its model (every pipeline sets
    ``PipelineResult.model``) and per-date params. Returns the in-memory
    ``PolicyBundle`` equivalent of what was written.

    With ``store`` (a ``BundleStore`` or its root directory) the finished
    export is additionally PUBLISHED into the content-addressed catalog
    under ``tenant`` (default: the bundle directory's name) — the bundle
    becomes a manifest of CAS pointers other replicas resolve via
    ``store://<root>#<tenant>`` sources, files shared with already-
    published tenants deduplicating to existing blobs.
    """
    model = getattr(result, "model", None)
    if model is None:
        raise ValueError(
            "result carries no model (PipelineResult.model is None) — "
            "was it produced by a pre-serve version of the pipelines?"
        )
    state = result.backward.policy_state()
    times = np.asarray(result.times, np.float64)
    n_dates = len(times) - 1
    verify_policy_compat("export_bundle", model, n_dates,
                         state["params1_by_date"])
    fp = policy_fingerprint(
        model, n_dates, dual_mode=result.dual_mode,
        holdings_combine=result.holdings_combine,
        cost_of_capital=result.cost_of_capital,
    )
    d = pathlib.Path(directory)
    meta_file = d / _META
    if meta_file.exists():
        # re-exporting the SAME policy config over itself is allowed (the
        # params are overwritten); a different one must refuse, like a
        # checkpoint dir would
        verify_fingerprint(d, fp, what="bundle dir")
    d.mkdir(parents=True, exist_ok=True)
    meta = {
        "format": _FORMAT,
        "model": _model_meta(model),
        "n_dates": n_dates,
        "times": times.tolist(),
        "adjustment_factor": float(result.adjustment_factor),
        "dual_mode": result.dual_mode,
        "holdings_combine": result.holdings_combine,
        "cost_of_capital": float(result.cost_of_capital),
        "sim_seed": result.sim_seed,
    }
    # model-health baseline (optional, additive — the fingerprint covers the
    # POLICY identity, not the baseline; a re-export refreshes it freely):
    # every pipeline attaches its training-feature sketch, the risk-neutral
    # ones also a pinned validation scenario set + hedge-error level
    sketch = getattr(result, "feature_sketch", None)
    validation = getattr(result, "validation", None)
    err0 = getattr(result, "hedge_error_baseline", None)
    if sketch is not None or validation is not None:
        meta["baseline"] = {
            "sketch": None if sketch is None else sketch.to_meta(),
            "validation": None if validation is None else validation.to_meta(),
            "hedge_error": None if err0 is None else float(err0),
        }
    # atomic: bundle.json is what load_bundle trusts to rebuild the model —
    # a torn write must leave the previous (complete) metadata or nothing
    atomic_write_text(meta_file, json.dumps(meta, indent=1, sort_keys=True))
    write_fingerprint(d, fp)
    policy_dir = d / _POLICY_SUBDIR
    if policy_dir.exists():
        # re-export of the same config overwrites: orbax refuses to re-save
        # an existing step even under force on this version, so clear first
        import shutil

        shutil.rmtree(policy_dir)
    save_checkpoint(policy_dir, 0, state)
    if store is not None:
        from orp_tpu.store.catalog import open_store

        st = store if hasattr(store, "publish") else open_store(store)
        st.publish(tenant if tenant is not None else d.name, d)
    return PolicyBundle(
        model=model, backward=BackwardResult.from_policy_state(state),
        times=times, adjustment_factor=float(result.adjustment_factor),
        dual_mode=result.dual_mode, holdings_combine=result.holdings_combine,
        cost_of_capital=float(result.cost_of_capital),
        sim_seed=result.sim_seed, fingerprint=fp,
        feature_sketch=sketch, validation=validation,
        hedge_error_baseline=None if err0 is None else float(err0),
    )


def load_bundle(directory: str | pathlib.Path) -> PolicyBundle:
    """Load and VERIFY a bundle: fingerprint side file against the recorded
    metadata, restored params against the recorded architecture.

    ``directory`` may also be a ``store://<root>#<tenant>[@version]`` URI:
    the tenant's manifest is resolved from the catalog, its CAS blobs
    digest-verified and materialized into the store's shared warm
    directory, and the load proceeds from there — bitwise identical to
    loading the directory the tenant was published from."""
    if isinstance(directory, str) and directory.startswith("store://"):
        from orp_tpu.store.catalog import open_store, parse_store_uri

        root, tenant_name, version = parse_store_uri(directory)
        return open_store(root).load(tenant_name, version)
    d = pathlib.Path(directory)
    meta_file = d / _META
    if not meta_file.exists():
        raise ValueError(f"{d} is not a policy bundle (no {_META})")
    meta = json.loads(meta_file.read_text())
    if meta.get("format") != _FORMAT:
        raise ValueError(
            f"{d}: unsupported bundle format {meta.get('format')!r} "
            f"(this loader reads {_FORMAT}; a pre-guard v1 bundle lacks "
            "the policy integrity digest — re-export it with the current "
            "code)"
        )
    model = _model_from_meta(meta["model"])
    n_dates = int(meta["n_dates"])
    fp = policy_fingerprint(
        model, n_dates, dual_mode=meta["dual_mode"],
        holdings_combine=meta["holdings_combine"],
        cost_of_capital=float(meta["cost_of_capital"]),
    )
    verify_fingerprint(d, fp, what="bundle dir")
    if latest_step(d / _POLICY_SUBDIR) != 0:
        raise ValueError(f"{d}: bundle has no saved policy step under "
                         f"{_POLICY_SUBDIR}/ — incomplete export?")
    state = load_checkpoint(d / _POLICY_SUBDIR, 0)
    # restore as device arrays in the model dtype ONCE here — the engine then
    # indexes into resident params instead of re-transferring per request
    for key in ("params1_by_date", "params2_by_date"):
        if key in state:
            state[key] = jax.tree.map(
                lambda x: jnp.asarray(x, model.dtype), state[key]
            )
    verify_policy_compat(f"load_bundle({d})", model, n_dates,
                         state["params1_by_date"])
    # serialized serving executables ride along when the export was --aot;
    # recording the dir (not deserializing here) keeps loading cheap and
    # leaves the fingerprint check to the engine that will actually execute
    has_aot = (d / "aot" / "aot.json").exists()
    sketch = validation = err0 = None
    baseline = meta.get("baseline")
    if baseline:
        from orp_tpu.obs.quality import FeatureSketch, ValidationSpec

        if baseline.get("sketch"):
            sketch = FeatureSketch.from_meta(baseline["sketch"])
        if baseline.get("validation"):
            validation = ValidationSpec.from_meta(baseline["validation"])
        err0 = baseline.get("hedge_error")
    return PolicyBundle(
        model=model,
        backward=BackwardResult.from_policy_state(state),
        times=np.asarray(meta["times"], np.float64),
        adjustment_factor=float(meta["adjustment_factor"]),
        dual_mode=meta["dual_mode"],
        holdings_combine=meta["holdings_combine"],
        cost_of_capital=float(meta["cost_of_capital"]),
        sim_seed=meta["sim_seed"],
        fingerprint=fp,
        aot_dir=d if has_aot else None,
        feature_sketch=sketch, validation=validation,
        hedge_error_baseline=None if err0 is None else float(err0),
    )
