"""Serving precision tiers: f32 (exact), bf16, int8-weight/f32-accum.

The serve forward is ~122 params of 8-wide matmuls — at that size the
device is bandwidth/dispatch bound, not FLOP bound, so the win from a
lower tier is the smaller parameter/activation traffic and the cheaper
matmul issue, not arithmetic throughput. The tier contract:

``f32``
    The historical path, byte-identical to what every ``*_oos`` ledger
    pin asserts. ``prepare_params``/``eval_model`` are exact identities
    (modulo the same ``asarray(model.dtype)`` cast the engine always
    applied), so nothing bitwise can move.
``bf16``
    Params, features, prices and the whole forward run in bfloat16 (the
    model is tier-replaced via :meth:`HedgeMLP.with_dtype`, so the SAME
    ``_date_outputs_core`` the training walk uses runs the bf16 trace).
    Outputs are cast back to f32 at the executable boundary — the serve
    API dtype is tier-invariant.
``int8``
    Weight-only quantization: per-date, per-tensor symmetric absmax
    int8 weights with an f32 scale, dequantized AFTER the date gather
    inside the executable; the forward then runs in full f32
    ("int8-weight/f32-accum"). Biases stay f32 (quantizing an 8-wide
    bias buys nothing and costs accuracy).

Non-f32 tiers are NOT bitwise and must never be promoted on bits:
tenant promotion goes through ``ServeHost.reload_tenant``'s paired-RQMC
quality band with the f32 incumbent as baseline (``require_same_bits=
False``, ``quality_band=...``) — see ``serve/bench.py``'s ``--precision``
drill, which commits the banded pins.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

#: the valid tiers, in decreasing precision order
TIERS = ("f32", "bf16", "int8")

#: quantized-leaf marker keys (a dict pytree node, so the date gather
#: ``x[date_idx]`` walks into it for free)
_QKEYS = frozenset({"q", "scale"})


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One serving precision tier. Hashable + frozen so it can ride jit
    static arguments and engine fingerprints."""

    tier: str = "f32"

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(
                f"precision tier {self.tier!r} not in {TIERS}")

    @property
    def is_f32(self) -> bool:
        return self.tier == "f32"

    def eval_dtype(self, model) -> Any:
        """The dtype request rows are padded/dispatched in."""
        return jnp.bfloat16 if self.tier == "bf16" else model.dtype


def normalize_precision(precision) -> PrecisionPolicy:
    """Accept a tier string or a :class:`PrecisionPolicy`."""
    if isinstance(precision, PrecisionPolicy):
        return precision
    return PrecisionPolicy(str(precision))


def eval_model(model, tier: str):
    """The model the executable actually runs: tier-replaced to bf16 for
    the bf16 tier (its ``dtype`` field drives every ``astype`` inside the
    shared forward), unchanged otherwise (int8 dequantizes to f32 and
    runs the f32 model)."""
    if tier == "bf16":
        return model.with_dtype(jnp.bfloat16)
    return model


def _is_quantized_leaf(node) -> bool:
    return isinstance(node, dict) and set(node.keys()) == _QKEYS


def quantize_tensor(x, *, accum_dtype=jnp.float32) -> dict:
    """Per-date, per-tensor symmetric absmax int8 quantization of a
    date-stacked ``(D, ...)`` weight. Returns ``{"q": int8, "scale":
    accum_dtype}`` with the scale broadcastable over the date axis."""
    x = jnp.asarray(x, accum_dtype)
    axes = tuple(range(1, x.ndim))
    absmax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0,
                      jnp.ones_like(absmax))
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale.astype(accum_dtype)}


def dequantize_params(tree):
    """Inverse of the weight quantization in :func:`prepare_params`:
    every ``{"q", "scale"}`` node becomes ``q * scale`` in the scale's
    dtype (f32 — the accumulate dtype), other leaves pass through."""
    return jax.tree.map(
        lambda t: (t["q"].astype(t["scale"].dtype) * t["scale"]
                   if _is_quantized_leaf(t) else t),
        tree, is_leaf=_is_quantized_leaf)


def prepare_params(params_by_date, tier: str, *, model_dtype=jnp.float32):
    """Tier-transform a date-stacked params pytree for device residency.

    ``f32``: the engine's historical ``asarray(model.dtype)`` cast —
    bitwise what it always served. ``bf16``: cast every leaf to bf16.
    ``int8``: weight leaves (dict key ``w*``) quantize per date/tensor;
    bias leaves stay ``model_dtype``.
    """
    if params_by_date is None:
        return None
    if tier == "f32":
        return jax.tree.map(lambda x: jnp.asarray(x, model_dtype),
                            params_by_date)
    if tier == "bf16":
        return jax.tree.map(lambda x: jnp.asarray(x, jnp.bfloat16),
                            params_by_date)
    if tier != "int8":
        raise ValueError(f"precision tier {tier!r} not in {TIERS}")

    def prep(path, x):
        key = path[-1]
        name = getattr(key, "key", None)
        if isinstance(name, str) and name.startswith("w"):
            return quantize_tensor(x, accum_dtype=model_dtype)
        return jnp.asarray(x, model_dtype)

    return jax.tree_util.tree_map_with_path(prep, params_by_date)
