"""Serving health: the stuck-dispatch watchdog + the ``orp doctor`` probe.

Two failure classes the guard layer could not reach before this module:

- **the hang** — every handled serve fault so far RAISES (transient
  dispatch errors, AOT execution failures, injected chaos). A wedged
  executable raises nothing: ``block_until_ready`` simply never returns,
  the resolve stage stops resolving, and every queued request ages out
  behind it. :class:`DispatchWatchdog` bounds the block — a batch that
  exceeds ``GuardPolicy.hard_wall_ms`` is FORCE-FAILED with
  :class:`~orp_tpu.guard.WatchdogTrip` (``guard/watchdog_trip``), the trip
  feeds the engine's AOT circuit breaker (a bucket that hangs repeatedly is
  demoted to jit exactly like one that raises repeatedly), and the
  batcher's bounded block-time retry re-dispatches the rows through a path
  that can answer. The waiter thread that was blocked is ABANDONED: XLA
  execution cannot be cancelled, so "force-fail" honestly means "stop
  waiting, leak the waiter" — which is also why the watchdog is opt-in.

- **the broken pod** — a serve process that will not come up has one of a
  short list of causes (no devices / wrong topology, unwritable compile
  cache, stale or foreign bundle artifacts, unwritable telemetry sink), and
  each surfaces as a deep stack trace from whichever layer hit it first.
  :func:`doctor_report` (CLI ``orp doctor``) runs the whole list up front
  and reports every finding in flag-speak — the first thing to run on a
  broken pod, before any simulation or compile spend.
"""

from __future__ import annotations

import threading
from concurrent.futures import TimeoutError as _FutureTimeoutError

from orp_tpu.guard.serve import WatchdogTrip
from orp_tpu.obs import count as obs_count
from orp_tpu.obs import flight


class _BlockWorker:
    """One daemon thread running blocking reads on the watchdog's behalf.

    The resolve stage hands it ``fn`` (a device block) and waits on the
    returned future with the hard-wall timeout; an abandoned worker (its
    current ``fn`` hung) finishes or leaks with the hang — either way it
    never touches a live watchdog again."""

    __slots__ = ("_q", "thread", "dead")

    def __init__(self):
        import queue

        self._q = queue.SimpleQueue()
        self.dead = False
        self.thread = threading.Thread(
            target=self._run, name="orp-serve-watchdog", daemon=True)
        self.thread.start()

    def submit(self, fn):
        from orp_tpu.serve.batcher import SlimFuture

        fut = SlimFuture()
        self._q.put((fn, fut))
        return fut

    def abandon(self):
        self.dead = True
        self._q.put(None)  # wakes an idle worker; a hung one exits on return

    def _run(self):
        while True:
            item = self._q.get()
            if item is None or self.dead:
                return
            fn, fut = item
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — delivered through the future
                fut.set_exception(e)
            if self.dead:
                return


class DispatchWatchdog:
    """Bound the resolve-stage block on an in-flight batch by a hard wall.

    ``block(fn, tag)`` runs ``fn()`` (the pending batch's blocking result
    read) on a helper thread and waits at most ``hard_wall_ms``. Inside the
    wall it is transparent — the result or exception propagates unchanged,
    and ``on_ok(tag)`` resets any hang streak. Past the wall it force-fails:
    emits ``guard/watchdog_trip``, feeds ``on_trip(tag)`` (the engine's
    circuit-breaker hook — ``HedgeEngine.watchdog_trip`` demotes a
    repeatedly-hanging AOT bucket to jit), abandons the stuck helper and
    raises :class:`WatchdogTrip` (a ``TransientDispatchError``: the
    batcher's block-time retry policy applies).

    One watchdog serves one batcher — the resolve stage is sequential, so
    a single helper thread is enough until a trip orphans it.
    """

    def __init__(self, hard_wall_ms: float, *, on_trip=None, on_ok=None):
        if hard_wall_ms <= 0:
            raise ValueError(f"hard_wall_ms={hard_wall_ms} must be > 0")
        self.hard_wall_s = float(hard_wall_ms) / 1e3
        self.on_trip = on_trip
        self.on_ok = on_ok
        self.trips = 0
        self._lock = threading.Lock()
        self._worker: _BlockWorker | None = None

    def block(self, fn, tag=None):
        with self._lock:
            w = self._worker
            if w is None or w.dead:
                w = _BlockWorker()
                self._worker = w
        fut = w.submit(fn)
        try:
            out = fut.result(timeout=self.hard_wall_s)
        except _FutureTimeoutError:
            with self._lock:
                self.trips += 1
                if self._worker is w:
                    self._worker = None
            w.abandon()
            obs_count("guard/watchdog_trip", key=str(tag))
            flight.record("watchdog_trip", tag=str(tag),
                          hard_wall_ms=self.hard_wall_s * 1e3,
                          trips=self.trips)
            if self.on_trip is not None:
                self.on_trip(tag)
            raise WatchdogTrip(
                f"in-flight batch (tag={tag}) exceeded the "
                f"{self.hard_wall_s * 1e3:.0f}ms dispatch hard wall; "
                "force-failed (the stuck waiter is abandoned)"
            ) from None
        if self.on_ok is not None:
            self.on_ok(tag)
        return out

    def close(self):
        with self._lock:
            w, self._worker = self._worker, None
        if w is not None:
            w.abandon()


# -- orp doctor ---------------------------------------------------------------


def _check(checks: list, name: str, ok: bool, detail: str,
           fix: str | None = None) -> bool:
    checks.append({"check": name, "ok": bool(ok), "detail": detail,
                   **({"fix": fix} if fix and not ok else {})})
    return bool(ok)


def _dir_writable(d) -> tuple[bool, str]:
    import os
    import pathlib
    import tempfile

    p = pathlib.Path(d)
    try:
        p.mkdir(parents=True, exist_ok=True)
        with tempfile.NamedTemporaryFile(dir=p, prefix=".orp_doctor_") as f:
            f.write(b"ok")
        return True, f"{p} is writable"
    except OSError as e:
        return False, f"{p}: {os.strerror(e.errno) if e.errno else e}"


def doctor_report(bundle_dir=None, *, mesh=None, cache_dir=None,
                  telemetry_dir=None, gateway=None, metrics=None,
                  quality=None, perf=None, fleet=None, store=None,
                  pilot=None, gateway_timeout_s: float = 5.0) -> dict:
    """One-shot environment/bundle self-check — the first thing to run on a
    broken pod. Returns ``{"ok": bool, "checks": [...]}`` where each check
    row carries ``check``/``ok``/``detail`` and, on failure, a ``fix`` in
    flag-speak (the CLI flag or command that repairs it).

    ``bundle_dir``  — optionally verify a policy bundle: format/fingerprint/
    policy-step digest (a full ``load_bundle``) plus its AOT topology
    coverage for THIS process's topology (``mesh`` — None = single device).
    ``cache_dir``   — persistent-compile-cache dir to probe (default: the
    ``enable_persistent_cache`` resolution: env ``ORP_JAX_CACHE_DIR``, else
    the repo ``.jax_cache``).
    ``telemetry_dir`` — optionally probe the obs sink target for
    ``--telemetry DIR`` runs.
    ``gateway``     — optionally probe a running ingest gateway
    (``"host:port"``): one TCP connect + ``orp-ingest`` PING/PONG round
    trip, the liveness check for a ``orp serve-gateway`` front.
    ``metrics``     — optionally probe the LIVE scrape of a gateway
    (``"host:port"``, the METRICS wire kind): the exposition must parse
    and carry the core serve series (request/latency, queue age, sheds) —
    a gateway that serves traffic but cannot be observed is a failing
    check, fixed in flag-speak.
    ``quality``     — optionally probe a bundle's MODEL-HEALTH plumbing
    (``orp doctor --quality DIR``): the bundle must carry the baked
    per-feature baseline sketch + pinned validation-set fingerprint
    (``orp export`` bakes both), and a shrunken hedge-quality estimate
    (``obs.quality.evaluate_quality``) must produce a parseable
    ``orp-quality-v1`` record with a nonzero RQMC confidence interval —
    the preflight for serve-time drift monitoring and the
    ``reload_tenant(quality_band=...)`` canary gate.
    ``perf``        — optionally probe the PERFORMANCE-observatory
    plumbing (``orp doctor --perf [LEDGER]``): ``jax.profiler`` importable
    with a writable trace-dir target (the ``orp profile --trace-dir``
    preflight), the ``orp-perf-v1`` ledger parseable AND appendable (a
    torn tail is tolerated, anything else is corruption), and the roofline
    peak table covering THIS process's ``device_kind`` — an uncovered kind
    still rooflines against the measured-matmul fallback, but the check
    says so in flag-speak because a fabricated-feeling fraction-of-peak is
    exactly what an operator should not discover mid-incident.
    ``fleet``        — probe a whole serve fleet from its ``topology.json``
    (``orp doctor --fleet topology.json``): PING every replica and every
    fleet gateway, read each gateway's routing view (the HEALTH wire
    kind's ``routing`` section — version, healthy set, per-replica health
    age, tenant-sample mapping) and verify ROUTING AGREEMENT: every
    gateway must map the same tenant sample to the same replicas under
    the same table version (disagreement means per-process salt crept
    into the hash — the ORP018 failure — or the gateways see different
    replica sets). Per-replica health ages are reported as the maximum
    staleness any gateway observes.
    ``store``       — probe a content-addressed bundle store
    (``orp doctor --store ROOT``): the catalog must parse, the CAS blob
    directory must be writable, and the catalog closure must be free of
    DANGLING references (a manifest pointing at bytes the CAS no longer
    holds means tenants that cannot activate — the failing row says which
    command re-publishes); orphan blobs are reported as reclaimable via
    ``orp store gc``, never as failures.
    ``pilot``       — probe a closed-loop pilot's plumbing from its
    ``orp-pilot-v1`` journal (``orp doctor --pilot JOURNAL``): the journal
    must parse (a torn tail is tolerated, anything else is corruption) and
    be appendable (``orp pilot retrain`` files requests into it), the last
    cycle's verdict must be PRESENT on its hash-linked promotions chain
    with every link verifying (a promoted/rejected cycle that left no
    chain verdict is an unauditable deploy), and the trigger sources named
    by the latest journaled config must be reachable — ``events_dir``
    readable, ``prices_path`` carrying at least ``calib_window`` rows — so
    a pilot that would silently never fire again is a failing row, not a
    mystery.
    ``gateway_timeout_s`` bounds every probe's connect AND every recv — a
    dead-but-ACCEPTING endpoint (the listener is up, nothing answers)
    becomes a failing check row within this budget, never an indefinite
    block.
    """
    checks: list[dict] = []
    # 1) devices + topology fingerprint: everything downstream keys on this
    try:
        import jax

        from orp_tpu.parallel.mesh import topology_fingerprint

        devs = jax.devices()
        n_want = None if mesh in (None, 0) else int(mesh)
        ok = n_want is None or n_want <= len(devs)
        # fingerprint the topology actually buildable HERE: an oversized
        # --mesh is its own (flag-speak) failure, not a backend crash
        topo = topology_fingerprint(None if (n_want in (None, 1) or not ok)
                                    else n_want)
        _check(checks, "devices", ok,
               f"{len(devs)} x {devs[0].device_kind} ({devs[0].platform}); "
               f"topology {topo}",
               fix=(f"--mesh {n_want} exceeds the {len(devs)} visible "
                    "devices — shrink --mesh or fix device visibility "
                    "(JAX_PLATFORMS / plugin init)" if not ok else None))
    except Exception as e:  # orp: noqa[ORP009] -- the report IS the emission: the probe failure becomes a failing check row the CLI prints
        _check(checks, "devices", False, f"{type(e).__name__}: {e}",
               fix="no JAX backend came up — check JAX_PLATFORMS and the "
                   "accelerator plugin/tunnel before anything else")
        topo = None
    # 2) persistent compile cache: unwritable -> every cold start pays the
    # full compile bill again (orp warm / --aot are no-ops)
    from orp_tpu.aot.cache import resolve_cache_dir

    cdir = resolve_cache_dir(cache_dir)
    if cdir is None:
        _check(checks, "compile_cache", True,
               "disabled by ORP_TESTS_NO_COMPILE_CACHE (kill-switch)")
    else:
        ok, detail = _dir_writable(cdir)
        _check(checks, "compile_cache", ok, detail,
               fix="point ORP_JAX_CACHE_DIR (or orp warm --cache-dir) at a "
                   "writable directory")
    # 3) the bundle: format gate, fingerprint, policy-step integrity digest
    if bundle_dir is not None:
        from orp_tpu.serve.bundle import load_bundle

        bundle = None
        try:
            bundle = load_bundle(bundle_dir)
            _check(checks, "bundle", True,
                   f"{bundle_dir}: {bundle.n_dates} dates, "
                   f"fingerprint {bundle.fingerprint[:12]}…")
        except (ValueError, OSError) as e:
            _check(checks, "bundle", False, str(e),
                   fix="re-export with `orp export --out DIR` (plus --aot "
                       "for serialized executables)")
        # 4) AOT coverage for THIS topology (only meaningful on a loadable
        # bundle; a jit fallback is safe but pays cold compiles)
        if bundle is not None:
            from orp_tpu.aot.bundle_exec import aot_status

            st = aot_status(bundle_dir, mesh=mesh)
            if not st["present"]:
                _check(checks, "bundle_aot", True,
                       "no AOT artifacts (jit serving; cold starts compile)")
            else:
                _check(checks, "bundle_aot", st["ok"],
                       st["detail"],
                       fix="re-export the executables for this topology: "
                           "`orp export --aot --aot-mesh "
                           f"{1 if mesh in (None, 0) else int(mesh)}`")
    # 5) model-health plumbing: baseline sketch + validation fingerprint
    # baked, quality record parseable with an honest (nonzero) CI
    if quality is not None:
        from orp_tpu.obs.quality import (evaluate_quality,
                                         validate_quality_record)
        from orp_tpu.serve.bundle import load_bundle

        _refix = ("re-export with the current code: `orp export --out DIR` "
                  "bakes the per-feature baseline sketch and the pinned "
                  "validation set the drift monitor and the "
                  "quality_band canary gate need")
        try:
            qb = load_bundle(quality)
        except (ValueError, OSError) as e:
            _check(checks, "quality", False, f"{quality}: {e}", fix=_refix)
        else:
            if qb.feature_sketch is None or qb.validation is None:
                missing = [w for w, v in (("baseline sketch",
                                           qb.feature_sketch),
                                          ("validation set", qb.validation))
                           if v is None]
                _check(checks, "quality", False,
                       f"{quality}: bundle bakes no {' or '.join(missing)} "
                       "(pre-quality export)", fix=_refix)
            else:
                try:
                    rec = evaluate_quality(
                        qb, n_paths=min(qb.validation.n_paths, 256),
                        replicates=2)
                except (ValueError, RuntimeError) as e:
                    _check(checks, "quality", False,
                           f"{quality}: quality estimate failed ({e})",
                           fix=_refix)
                else:
                    problems = validate_quality_record(rec)
                    he = rec.get("hedge_error", {})
                    if not problems and not he.get("ci95", 0.0) > 0.0:
                        problems = ["ci95 is zero — replicates collapsed "
                                    "(identical scrambles?)"]
                    base = qb.hedge_error_baseline
                    _check(checks, "quality", not problems,
                           (f"{quality}: hedge_error {he.get('mean', 0):.5g}"
                            f" ± {he.get('ci95', 0):.2g} (RQMC, "
                            f"{rec.get('replicates')} replicates)"
                            + (f"; training baseline {base:.5g}"
                               if base is not None else "")
                            + f"; validation "
                              f"{qb.validation.fingerprint()[:48]}…"
                            if not problems else
                            f"{quality}: quality record invalid: "
                            f"{problems}"),
                           fix=_refix)
    # 6) obs sink target
    if telemetry_dir is not None:
        ok, detail = _dir_writable(telemetry_dir)
        _check(checks, "telemetry_sink", ok, detail,
               fix="--telemetry DIR must name a writable directory "
                   "(events.jsonl streams live)")
    # 7) ingest gateway liveness: connect + PING/PONG over orp-ingest-v1
    if gateway is not None:
        from orp_tpu.serve.gateway import GatewayClient

        addr, _, port = str(gateway).rpartition(":")
        try:
            with GatewayClient(addr or "127.0.0.1", int(port),
                               timeout_s=float(gateway_timeout_s)) as client:
                ok = client.ping()
            _check(checks, "gateway", ok,
                   f"{gateway}: PING/PONG {'ok' if ok else 'FAILED'}",
                   fix="the endpoint answered but not in orp-ingest — "
                       "is something else listening on that port?")
        # RuntimeError covers GatewayError (connection dropped mid-reply:
        # wrong service, or a gateway mid-drain); socket.timeout (an
        # OSError) covers the dead-but-accepting endpoint, surfaced within
        # gateway_timeout_s — the probe's whole job is to turn ANY of these
        # into a failing check row, never a traceback or an open-ended wait
        except (OSError, ValueError, RuntimeError) as e:
            _check(checks, "gateway", False,
                   f"{gateway}: {type(e).__name__}: {e}"
                   if not str(e) else f"{gateway}: {e}",
                   fix="start the front with `orp serve-gateway --bundle "
                       "DIR --port N` (or fix the host:port); a connect "
                       "that hangs past the timeout is a dead-but-accepting "
                       "endpoint — restart it")
    # 8) live metrics scrape: the exposition must parse AND carry the core
    # serve series — an unobservable gateway fails its fleet (no health
    # signal to drive REDIRECTs on), even while it serves
    if metrics is not None:
        from orp_tpu.serve.gateway import GatewayClient
        from orp_tpu.serve.scrape import parse_prometheus

        core = ("serve_gateway_rows", "serve_queue_age_seconds",
                "guard_shed")
        addr, _, port = str(metrics).rpartition(":")
        try:
            with GatewayClient(addr or "127.0.0.1", int(port),
                               timeout_s=float(gateway_timeout_s)) as client:
                text = client.metrics()
                # the HEALTH probe rides along and EXPLICITLY requests the
                # serving process's flight-recorder dump (when armed) — a
                # doctor visit leaves the black box on disk; plain health
                # probes (orp top) never write
                health = client.health(dump_flight=True)
            series = parse_prometheus(text)
            missing = [n for n in core if n not in series]
            flight_note = (
                f"; flight ring {health.get('flight_recorded', 0)} event(s)"
                + (f" dumped to {health['flight_dump']}"
                   if health.get("flight_dump") else ""))
            _check(checks, "metrics", not missing,
                   (f"{metrics}: {len(series)} series, core present"
                    f"{flight_note}"
                    if not missing else
                    f"{metrics}: exposition parsed but lacks core serve "
                    f"series {missing}"),
                   fix="the endpoint answers METRICS frames but not with "
                       "the serve exposition — upgrade the gateway (`orp "
                       "serve-gateway` from this build pre-interns the "
                       "core series)")
        except (OSError, ValueError, RuntimeError) as e:
            _check(checks, "metrics", False,
                   f"{metrics}: {type(e).__name__}: {e}"
                   if not str(e) else f"{metrics}: {e}",
                   fix="no live scrape at that address — probe the ingest "
                       "port of a running `orp serve-gateway` (the METRICS "
                       "wire kind shares it), or fix host:port")
    # 9) the fleet: every replica + gateway answers, and every gateway
    # agrees on the routing table (the fleet's founding invariant)
    if fleet is not None:
        _fleet_checks(checks, fleet, timeout_s=float(gateway_timeout_s))
    # 10) performance observatory: profiler + trace dir, ledger, peak table
    if perf is not None:
        import tempfile

        from orp_tpu.obs import perf as perf_mod

        import pathlib as _pathlib

        try:
            import jax.profiler as _profiler

            ok = hasattr(_profiler, "trace")
            w_ok, w_detail = _dir_writable(
                _pathlib.Path(tempfile.gettempdir()) / "orp_profile_probe")
            _check(checks, "perf_profiler", ok and w_ok,
                   ("jax.profiler.trace available; trace target "
                    f"{w_detail}") if ok else
                   "this jax build exposes no jax.profiler.trace",
                   fix=("run `orp profile` without --trace-dir (the span "
                        "breakdown still works), or upgrade jaxlib for "
                        "perfetto captures" if not ok else
                        "point --trace-dir at a writable directory"))
        except Exception as e:  # orp: noqa[ORP009] -- the report IS the emission: the probe failure becomes a failing check row
            _check(checks, "perf_profiler", False,
                   f"{type(e).__name__}: {e}",
                   fix="no jax backend came up — fix JAX_PLATFORMS before "
                       "profiling anything")
        ledger_path = (perf if isinstance(perf, str)
                       else perf_mod.PERF_LEDGER_FILE)
        try:
            records, problems = perf_mod.read_ledger(ledger_path)
            invalid = sum(bool(perf_mod.validate_perf_record(r))
                          for r in records)
            lp = _pathlib.Path(ledger_path)
            if lp.exists():
                # appendable probe WITHOUT a side effect: open-for-append
                # on the existing file (never creates an empty ledger)
                with open(lp, "a"):
                    pass
                app = "appendable"
            else:
                ok_dir, dir_detail = _dir_writable(lp.parent
                                                   if str(lp.parent) else ".")
                if not ok_dir:
                    raise OSError(f"parent not writable ({dir_detail})")
                app = "absent (first run seeds it); parent writable"
            ok = invalid == 0
            _check(checks, "perf_ledger", ok,
                   f"{ledger_path}: {len(records)} record(s), {app}"
                   + (f", {len(problems)} torn-tail line(s) tolerated"
                      if problems else "")
                   + (f"; {invalid} INVALID record(s)" if invalid else ""),
                   fix="the ledger holds records that fail the orp-perf-v1 "
                       "schema — move it aside and reseed with `orp "
                       "serve-bench --ledger PATH` / `orp profile`")
        except (OSError, ValueError) as e:
            _check(checks, "perf_ledger", False, f"{ledger_path}: {e}",
                   fix="move the corrupt ledger aside; the next `orp "
                       "profile` / `orp serve-bench --ledger PATH` run "
                       "reseeds it")
        try:
            import jax

            kind = jax.devices()[0].device_kind  # orp: noqa[ORP011] -- topology introspection: the kind is fleet-wide
            peak, source = perf_mod.peak_for(kind)
            _check(checks, "perf_peaks", source == "table",
                   (f"PEAK_TABLE covers {kind!r} "
                    f"({peak['flops_per_s'] / 1e12:.1f} TFLOP/s f32 ceiling)"
                    if source == "table" else
                    f"{kind!r} not in PEAK_TABLE — roofline fractions fall "
                    f"back to the measured-matmul peak "
                    f"({peak['flops_per_s'] / 1e9:.1f} GFLOP/s)"),
                   fix=f"add a PEAK_TABLE entry for {kind!r} in "
                       "orp_tpu/obs/perf.py (published per-chip FLOP/s + "
                       "HBM bytes/s) — until then frac_peak_* is against "
                       "the measured-matmul fallback and bytes/s fractions "
                       "are absent")
        except Exception as e:  # orp: noqa[ORP009] -- the report IS the emission: the probe failure becomes a failing check row
            _check(checks, "perf_peaks", False, f"{type(e).__name__}: {e}",
                   fix="no jax backend came up — fix JAX_PLATFORMS first")
    # 11) the bundle store: catalog parseable, CAS writable, closure clean
    if store is not None:
        from orp_tpu.store.catalog import open_store

        try:
            st = open_store(store)
            stats = st.stats()
        except (OSError, ValueError, KeyError) as e:
            _check(checks, "store_catalog", False, f"{store}: {e}",
                   fix="the catalog does not parse as orp-catalog-v1 — "
                       "move it aside and re-publish the tenants with "
                       "`orp store put --root ROOT --bundle DIR "
                       "--tenants NAME[,…]`")
        else:
            _check(checks, "store_catalog", True,
                   f"{store}: {stats['tenants']} tenant(s), "
                   f"{stats['manifests']} manifest(s), {stats['blobs']} "
                   f"blob(s) ({stats['blob_bytes']} bytes), dedup ratio "
                   f"{stats['dedup_ratio']}")
            ok, detail = _dir_writable(st.cas.blobs_dir)
            _check(checks, "store_cas", ok, detail,
                   fix="the CAS blob directory must be writable for "
                       "`orp store put` / export publishing to land")
            # dangling refs FAIL (tenants that cannot activate); orphan
            # blobs are just bytes awaiting gc — ok, with the reclaim note
            orphan_note = (
                f"; {stats['orphan_blobs']} orphan blob(s) "
                f"({stats['orphan_bytes']} bytes) reclaimable via "
                "`orp store gc`" if stats["orphan_blobs"] else "")
            _check(checks, "store_refs", stats["dangling_refs"] == 0,
                   (f"catalog closure clean{orphan_note}"
                    if stats["dangling_refs"] == 0 else
                    f"{stats['dangling_refs']} DANGLING blob reference(s) "
                    "— the catalog points at bytes the CAS no longer "
                    "holds; those tenants cannot activate"),
                   fix="re-publish the affected tenants with `orp store "
                       "put` (the missing blobs re-land content-addressed)")
    # 12) the pilot loop: journal parseable + appendable, the last cycle's
    # verdict chain-linked, and every configured trigger source reachable
    if pilot is not None:
        import pathlib as _pathlib

        from orp_tpu.pilot import journal as _pj

        jp = _pathlib.Path(pilot)
        records: list[dict] = []
        try:
            records, problems = _pj.read_journal(jp)
            if jp.exists():
                # appendable probe WITHOUT a side effect (perf-ledger
                # discipline): open-for-append, never create
                with open(jp, "a"):
                    pass
                app = "appendable"
            else:
                ok_dir, dir_detail = _dir_writable(
                    jp.parent if str(jp.parent) else ".")
                if not ok_dir:
                    raise OSError(f"parent not writable ({dir_detail})")
                app = "absent (the first cycle seeds it); parent writable"
            _check(checks, "pilot_journal", True,
                   f"{jp}: {len(records)} record(s), {app}"
                   + (f", {len(problems)} torn-tail line(s) tolerated"
                      if problems else ""))
        except (OSError, ValueError) as e:
            _check(checks, "pilot_journal", False, f"{jp}: {e}",
                   fix="the journal was edited or its directory is not "
                       "writable — move the corrupt file aside; the next "
                       "cycle (or `orp pilot retrain --journal PATH`) "
                       "reseeds it")
        cid, recs = _pj.last_cycle(records)
        if cid is None:
            _check(checks, "pilot_cycle", True,
                   "no cycles journaled yet (the loop has not fired)")
        else:
            state = recs[-1].get("state")
            want = {"promoted": "promote", "rejected": "reject"}.get(state)
            chain = recs[-1].get("chain")
            if state not in _pj.TERMINAL_STATES:
                _check(checks, "pilot_cycle", True,
                       f"cycle {cid} parked at {state!r} — resumable "
                       "(PilotController.resume() continues it from the "
                       "journal)")
            elif want is None:
                _check(checks, "pilot_cycle", True,
                       f"cycle {cid} failed: "
                       f"{recs[-1].get('error', 'journaled error')} — the "
                       "next accepted trigger starts a fresh cycle")
            elif not chain:
                _check(checks, "pilot_cycle", False,
                       f"cycle {cid} {state} with NO promotions chain "
                       "configured — the verdict is unauditable",
                       fix="construct the ServeHost with "
                           "promotion_chain=PATH (or run under "
                           "--telemetry) so every pilot verdict lands "
                           "hash-linked")
            else:
                from orp_tpu.obs.manifest import chain_verify, read_chain

                try:
                    cv = chain_verify(chain)
                    actions = [r.get("action") for r in read_chain(chain)]
                    ok = bool(cv["ok"]) and want in actions
                    _check(checks, "pilot_cycle", ok,
                           f"cycle {cid} {state}; chain {chain}: "
                           f"{cv['length']} verdict(s), "
                           + ("links verified" if cv["ok"] else
                              f"BROKEN ({'; '.join(cv['problems'][:2])})")
                           + ("" if want in actions else
                              f"; no {want!r} verdict on the chain"),
                           fix="the chain and the journal disagree about "
                               "the last cycle — verify with `orp report`/"
                               "chain_verify, move the edited chain aside, "
                               "and let the next reload reseed it")
                except OSError as e:
                    _check(checks, "pilot_cycle", False,
                           f"cycle {cid} {state}; chain {chain}: {e}",
                           fix="the journaled chain path is unreadable — "
                               "restore it or re-point the host's "
                               "promotion_chain")
        conf = _pj.latest_config(records)
        if conf is None:
            _check(checks, "pilot_triggers", True,
                   "no config journaled yet — manual requests "
                   "(`orp pilot retrain --journal PATH`) are the only "
                   "reachable source until a controller runs")
        else:
            notes: list[str] = []
            fails: list[str] = []
            fixes: list[str] = []
            ed = conf.get("events_dir")
            if ed:
                if _pathlib.Path(ed).is_dir():
                    notes.append(f"events_dir {ed} readable")
                else:
                    fails.append(f"events_dir {ed} is not a readable "
                                 "directory (drift trips unreachable)")
                    fixes.append("point PilotConfig.events_dir at the "
                                 "flight-recorder dump dir (RECORDER."
                                 "arm(DIR))")
            pp = conf.get("prices_path")
            if pp:
                need = conf.get("calib_window") or 0
                try:
                    with open(pp) as f:
                        rows = sum(1 for ln in f if ln.strip())
                    if rows >= need:
                        notes.append(f"prices_path {pp}: {rows} row(s) "
                                     f">= calib_window {need}")
                    else:
                        fails.append(f"prices_path {pp}: {rows} row(s) < "
                                     f"calib_window {need} — calibration "
                                     "triggers can never fire")
                        fixes.append("widen the feed or lower "
                                     "PilotConfig.calib_window")
                except OSError as e:
                    fails.append(f"prices_path {pp}: {e}")
                    fixes.append("restore the market feed file or re-point "
                                 "PilotConfig.prices_path")
            if not ed and not pp:
                notes.append("config names no events_dir/prices_path — "
                             "drift and calibration polls are fed "
                             "in-process; manual requests reachable")
            _check(checks, "pilot_triggers", not fails,
                   "; ".join(fails + notes) or "nothing configured",
                   fix="; ".join(fixes) if fixes else None)
    # always-on: the project-wide lock-discipline pass (pure AST over the
    # installed package — no device, ~100 ms). A finding here means a
    # deployed build whose serve/store planes carry a known race or
    # deadlock shape; the fleet drill should not be how it is discovered.
    try:
        from orp_tpu.lint.concurrency import analyze_paths, build_analyzer
        from orp_tpu.lint.engine import DEFAULT_LINT_ROOT

        conc = analyze_paths([DEFAULT_LINT_ROOT])
        stats = build_analyzer([DEFAULT_LINT_ROOT]).stats()
        _check(checks, "lint_concurrency", not conc,
               (f"{stats['classes']} classes / {stats['locks']} locks / "
                f"{stats['edges']} order edges indexed; "
                + (f"{len(conc)} unsuppressed finding(s): "
                   + "; ".join(f.render() for f in conc[:3])
                   if conc else "no unsuppressed findings")),
               fix="run `orp lint --concurrency` and fix (or reasoned-"
                   "noqa) every ORP020/ORP021/ORP022 finding" if conc
                   else None)
    except Exception as e:  # orp: noqa[ORP009] -- the report IS the emission: the probe failure becomes a failing check row the CLI prints
        _check(checks, "lint_concurrency", False,
               f"{type(e).__name__}: {e}",
               fix="the concurrency analyzer crashed on this install — "
                   "run `orp lint --concurrency` for the traceback")
    return {"ok": all(c["ok"] for c in checks), "checks": checks}


def _fleet_checks(checks: list, topology, *, timeout_s: float) -> None:
    """The ``--fleet`` probe battery: replica liveness, gateway liveness,
    routing-table agreement across gateways, per-replica health age."""
    from orp_tpu.serve.fleet import ROUTE_SAMPLE, FleetError, load_topology
    from orp_tpu.serve.gateway import GatewayClient

    try:
        topo = load_topology(topology)
    except FleetError as e:
        _check(checks, "fleet_topology", False, str(e),
               fix='write topology.json as {"gateways": ["host:port", …], '
                   '"replicas": {"name": "host:port", …}}')
        return
    _check(checks, "fleet_topology", True,
           f"{topology}: {len(topo['replicas'])} replica(s), "
           f"{len(topo['gateways'])} gateway(s)")
    # every replica: one PING + health round trip through its own gateway
    for r in topo["replicas"]:
        try:
            with GatewayClient(r.addr, r.port, timeout_s=timeout_s) as c:
                ok = c.ping()
                doc = c.health()
            draining = bool(doc.get("draining"))
            _check(checks, f"replica:{r.name}", ok and not draining,
                   f"{r.addr}:{r.port}: PING "
                   f"{'ok' if ok else 'FAILED'}"
                   + ("; DRAINING (its tenants are remapping)"
                      if draining else ""),
                   fix=f"restart the replica's serve-gateway on "
                       f"{r.addr}:{r.port} (its tenants rendezvous onto "
                       "the survivors meanwhile)")
        except (OSError, ValueError, RuntimeError) as e:
            _check(checks, f"replica:{r.name}", False,
                   f"{r.addr}:{r.port}: {type(e).__name__}: {e}",
                   fix=f"restart the replica's serve-gateway on "
                       f"{r.addr}:{r.port} (its tenants rendezvous onto "
                       "the survivors meanwhile)")
    # every gateway: liveness + its ROUTING VIEW over a fixed tenant sample
    views = {}
    for addr, port in topo["gateways"]:
        target = f"{addr}:{port}"
        try:
            with GatewayClient(addr, port, timeout_s=timeout_s) as c:
                ok = c.ping()
                doc = c.health(route=list(ROUTE_SAMPLE))
            routing = doc.get("routing")
            if routing is None:
                _check(checks, f"gateway:{target}", False,
                       f"{target}: answers but exports no routing view",
                       fix="this is a plain serving gateway, not a fleet "
                           "router — start it with `orp serve-gateway "
                           "--fleet topology.json`")
                continue
            views[target] = routing
            unhealthy = [n for n in routing.get("replicas", ())
                         if n not in (routing.get("healthy") or ())]
            _check(checks, f"gateway:{target}", ok,
                   f"{target}: routing {routing.get('version')}, "
                   f"{len(routing.get('healthy') or ())}/"
                   f"{len(routing.get('replicas') or ())} replicas "
                   "healthy"
                   + (f" (unhealthy: {unhealthy})" if unhealthy else ""),
                   fix=f"restart the fleet gateway on {target}")
        except (OSError, ValueError, RuntimeError) as e:
            _check(checks, f"gateway:{target}", False,
                   f"{target}: {type(e).__name__}: {e}",
                   fix=f"start the fleet gateway: `orp serve-gateway "
                       f"--fleet {topology} --port {port}`")
    # routing agreement: same sample -> same replica from EVERY gateway
    if len(views) >= 1:
        versions = {v.get("version") for v in views.values()}
        maps = [v.get("map") or {} for v in views.values()]
        agree = len(versions) == 1 and all(m == maps[0] for m in maps[1:])
        # worst case wins deterministically: None (never probed ok) beats
        # any numeric age, larger beats smaller — order-independent
        ages = {}
        for v in views.values():
            for name, age in (v.get("ages_s") or {}).items():
                if name in ages and (ages[name] is None or age is None):
                    ages[name] = None
                elif name not in ages or age > ages[name]:
                    ages[name] = age
        _check(checks, "fleet_routing", agree,
               (f"{len(views)} gateway(s) agree: version "
                f"{next(iter(versions))}, {len(maps[0])} sampled tenants "
                f"map identically; health ages (max) {ages}"
                if agree else
                f"gateways DISAGREE: versions {sorted(versions)} — same "
                "tenant sample maps differently across gateways"),
               fix="the rendezvous table diverged: make sure every "
                   "gateway runs the same topology.json and the same "
                   "build (per-process salt in routing code is the "
                   "ORP018 lint failure)")
