"""Batched low-latency policy evaluation with shape-bucketed executables.

The serving workload is ``phi_t(state)`` for arbitrary request sizes — one
policyholder, a branch office's 7, a book of 10^6. Naive jit recompiles per
batch shape; here every request is padded up to the next power-of-two
*bucket*, so the whole size spectrum hits a small fixed set of compiled
executables (log2(max/min) + 1 of them), each compiled exactly once. The
date index and the cost-of-capital margin are traced scalars, so serving all
rebalance dates shares the same executables.

The forward is the ONE definition the training walk and the replay use
(``train/backward.py:_date_outputs_core`` — full-f32 matmul precision, all
three dual-mode combines), so a served ``(phi, psi, value)`` is bit-identical
to the corresponding ``*_oos`` ledger column on the same inputs.

Spans wrap pad / dispatch / unpad: under an active telemetry session
(``orp_tpu/obs``) they land in the shared registry
(``span_seconds{name="serve/..."}``) and event sink and annotate profiler
captures; with telemetry off they fall back to the bare
``utils/profiling.trace`` TraceAnnotation — exactly the pre-obs behavior,
so an XProf capture of an untelemetered server still shows the serving
phases (the annotation cost is what serving always paid; only the
recording layer is new and session-gated).
"""

from __future__ import annotations

import functools
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from orp_tpu.guard import inject as _inject
from orp_tpu.guard.serve import CircuitBreaker
from orp_tpu.lint.trace_audit import compile_count
from orp_tpu.obs import count as obs_count
from orp_tpu.obs import devprof as _devprof
from orp_tpu.obs import enabled as obs_enabled
from orp_tpu.obs import span as obs_span
from orp_tpu.serve.precision import (
    dequantize_params,
    eval_model,
    normalize_precision,
    prepare_params,
)
from orp_tpu.train.backward import _date_outputs_core, _split_holdings
from orp_tpu.utils.profiling import trace


def span(name, attrs=None):
    """Telemetry span when a session is active, plain TraceAnnotation
    otherwise (see module docstring)."""
    return obs_span(name, attrs) if obs_enabled() else trace(name)


@functools.partial(jax.jit, static_argnames=("model", "dual_mode",
                                             "holdings_combine", "precision"))
def _eval_core(model, p1_all, p2_all, date_idx, feats, prices,
               cost_of_capital, *, dual_mode, holdings_combine,
               precision="f32"):
    """One bucket-shaped executable: gather the date's params, run the
    training walk's fused per-date outputs. ``date_idx`` is traced — one
    compile covers every rebalance date at this bucket size.

    ``precision`` (static) selects the serving tier (serve/precision.py):
    ``f32`` traces exactly the historical program, ``int8`` dequantizes
    the gathered weights back to f32 before the (f32-accumulate) forward,
    ``bf16`` runs the tier-replaced model end to end and casts the
    outputs back to f32 at the boundary — the serve API dtype is
    tier-invariant."""
    p1 = jax.tree.map(lambda x: x[date_idx], p1_all)
    p2 = jax.tree.map(lambda x: x[date_idx], p2_all)
    if precision == "int8":
        p1 = dequantize_params(p1)
        p2 = dequantize_params(p2)
    m = eval_model(model, precision)
    # shared-mode g_pre collapses to the stored (post-quantile) weights'
    # value — the replay semantics (train/replay.py docstring), the only
    # ones reconstructible from per-date snapshots
    g_pre = (
        m.value(p1, feats, prices)
        if dual_mode == "shared" else jnp.zeros((), m.dtype)
    )
    v, comb, _ = _date_outputs_core(
        m, p1, p2, feats, prices,
        jnp.zeros_like(prices), jnp.zeros(feats.shape[:1], m.dtype),
        cost_of_capital, g_pre,
        dual_mode=dual_mode, holdings_combine=holdings_combine,
    )
    phi, psi = _split_holdings(comb)
    if precision == "bf16":
        phi = phi.astype(jnp.float32)
        psi = psi.astype(jnp.float32)
        v = v.astype(jnp.float32)
    return phi, psi, v


def next_bucket(n: int, *, min_bucket: int = 8) -> int:
    """Smallest power-of-two >= n, floored at ``min_bucket``. Empty
    batches never reach bucketing: ``HedgeEngine.evaluate_async``
    short-circuits ``n == 0`` before dispatch (an all-padding bucket
    would bill a full device execute for zero rows)."""
    if n < 1:
        raise ValueError(
            f"batch of {n} rows never dispatches — empty requests "
            "short-circuit in evaluate_async before bucketing")
    return max(min_bucket, 1 << (n - 1).bit_length())


class PendingEval:
    """An in-flight bucket evaluation: the executable has been DISPATCHED
    (XLA's async runtime owns it now) but nobody has blocked on the result.

    This is the unit the continuous batcher overlaps: while one pending
    evaluation executes on device, the dispatch loop admits and pads the
    next one. ``result()`` blocks device-side, unpads, and returns the
    ``(phi, psi, value)`` host arrays — bitwise what a blocking
    ``HedgeEngine.evaluate`` of the same rows returns, because it IS the
    same dispatch, split at the block point.
    """

    __slots__ = ("_phi", "_psi", "_v", "_n", "_has_prices", "bucket",
                 "_prof", "_t_dispatch")

    def __init__(self, phi, psi, v, n: int, has_prices: bool, bucket: int,
                 prof=None, t_dispatch: float = 0.0):
        self._phi = phi
        self._psi = psi
        self._v = v
        self._n = int(n)
        self._has_prices = has_prices
        self.bucket = int(bucket)
        # device-time attribution (obs/devprof, flag-gated): the dispatch
        # instant + the live DevProf, stamped by evaluate_async; None when
        # attribution is off (the zero-cost default)
        self._prof = prof
        self._t_dispatch = t_dispatch

    def result(self):
        """Block until the device finishes, then slice the padding off:
        ``(phi, psi, value)`` host numpy arrays of the requested rows
        (``value`` None when the request carried no prices)."""
        n = self._n
        inj = _inject.active()
        if inj is not None:
            # chaos harness: the BLOCK-time fault site — a hung execute is
            # delay here past GuardPolicy.hard_wall_ms (the watchdog's
            # prey), a block-surfaced transient is fail, a loss discovered
            # at completion is device_loss
            inj.fire("serve/execute", bucket=self.bucket)
        prof = self._prof
        t_block = time.perf_counter() if prof is not None else 0.0
        phi, psi, v = jax.block_until_ready((self._phi, self._psi, self._v))
        if prof is not None:
            # serial-device attribution: this dispatch's wall splits into
            # queue vs device seconds (serve/device_seconds{bucket}) and
            # feeds the rolling utilization gauge
            prof.complete(self._t_dispatch, t_block, bucket=self.bucket)
        with span("serve/unpad"):
            phi = np.asarray(phi)[:n]
            psi = np.asarray(psi)[:n]
            value = np.asarray(v)[:n] if self._has_prices else None
        return phi, psi, value


class HedgeEngine:
    """Evaluate a hedge policy (a ``PolicyBundle`` or a ``PipelineResult``
    carrying its model) for arbitrary request sizes.

    ``evaluate(date_idx, states[, prices])`` pads the request to its bucket,
    dispatches the bucket-shaped executable, and slices the padding back off.
    ``hits``/``misses`` count bucket-cache hits (miss = first request landing
    in a bucket = the one compile that bucket ever pays).

    **AOT bundles**: a policy loaded from a bundle exported with
    ``orp export --aot`` carries serialized per-bucket executables
    (``orp_tpu/aot/bundle_exec.py``). They are deserialized HERE, at
    construction, and requests landing in those buckets execute them
    directly — zero XLA compiles on a cold process. Any fingerprint or
    deserialization mismatch warns once and keeps the jit path
    (``use_aot=False`` opts out entirely, e.g. for A/B timing).

    **Mesh serving**: ``mesh`` (a ``("paths",)`` device mesh, an int device
    count, or a ``parallel.mesh.MeshSpec``) turns every evaluation into a
    batch-sharded program — request rows sharded over the mesh, params
    replicated, padding rounded up so every shard is equal. The forward is
    per-row (no cross-row reductions), so sharded results are BITWISE the
    single-device ones (pinned in tests/test_mesh_native.py); the jit cache
    keys on input shardings, so executables are per (bucket, topology) with
    no extra bookkeeping, and AOT bundles resolve the matching
    per-topology executable set (``aot/<topo>/``).
    """

    def __init__(self, policy, *, min_bucket: int = 8, max_bucket: int = 1 << 20,
                 use_aot: bool = True, aot_failure_threshold: int = 3,
                 mesh=None, precision="f32"):
        model = getattr(policy, "model", None)
        if model is None:
            raise ValueError(
                "policy carries no model — pass a PolicyBundle or a "
                "PipelineResult produced by the current pipelines"
            )
        bw = policy.backward
        if bw.params1_by_date is None:
            raise ValueError("policy has no per-date params to serve")
        self.model = model
        self.dual_mode = policy.dual_mode
        self.holdings_combine = policy.holdings_combine
        self.cost_of_capital = float(policy.cost_of_capital)
        self.min_bucket = min_bucket
        self.max_bucket = max_bucket
        from orp_tpu.parallel.mesh import (as_mesh, path_sharding,
                                           replicated_sharding)

        self.mesh = as_mesh(mesh)
        if self.mesh is not None:
            self._rows = path_sharding(self.mesh, 2)
            self._rep = replicated_sharding(self.mesh)
        else:
            self._rows = self._rep = None
        # precision tier (serve/precision.py): f32 prepared params are the
        # historical asarray(model.dtype) cast — byte-identical serving;
        # bf16/int8 transform the stacks ONCE here, off the hot path
        self.precision = normalize_precision(precision)
        self._eval_dt = self.precision.eval_dtype(model)
        self._np_dt = np.dtype(jnp.dtype(self._eval_dt))
        put = (
            (lambda x: x) if self.mesh is None
            # replicate the per-date params across the mesh ONCE here — the
            # sharded eval program reads them collective-free on every shard
            # (tier-preserving: the prepared leaves already carry their
            # tier's dtype, int8 included)
            else (lambda x: jax.device_put(x, self._rep))
        )
        tier = self.precision.tier
        # device-resident once; every request indexes into these
        self._p1 = jax.tree.map(
            put, prepare_params(bw.params1_by_date, tier,
                                model_dtype=model.dtype))
        p2 = prepare_params(bw.params2_by_date, tier,
                            model_dtype=model.dtype)
        self._p2 = self._p1 if p2 is None else jax.tree.map(put, p2)
        self.n_dates = int(jax.tree.leaves(self._p1)[0].shape[0])
        # price legs per request row (risky legs then bond) — the one
        # definition evaluate() and the AOT exporter both shape against
        self.n_instruments = (
            2 if model.constrain_self_financing else model.n_outputs)
        self.hits = 0
        self.misses = 0
        self.aot_hits = 0
        self._buckets: set[int] = set()
        # mixed-date megakernel executables key the same bucket sizes but
        # are distinct programs — separate first-touch accounting
        self._mixed_buckets: set[int] = set()
        # deserialized per-bucket executables from an --aot bundle: requests
        # in these buckets never touch the jit cache (load_aot returns {} —
        # after ONE warning — when the artifacts don't fit this process)
        self._aot = {}
        # runtime circuit breaker (orp_tpu/guard): aot_failure_threshold
        # CONSECUTIVE execution failures of one bucket's serialized
        # executable demote that bucket to the always-correct jit path for
        # the process lifetime — the steady-state extension of load_aot's
        # construction-time fallback. Each individual failure already falls
        # back to jit for its own request (bitwise-equal program).
        self._breaker = CircuitBreaker(aot_failure_threshold,
                                       what="aot_bucket")
        aot_dir = getattr(policy, "aot_dir", None)
        if use_aot and aot_dir is not None:
            from orp_tpu.aot.bundle_exec import load_aot

            # per-topology resolution: the mesh names which executable set
            # under <bundle>/aot/<topo>/ fits this engine (aot/bundle_exec.py)
            self._aot = load_aot(
                aot_dir,
                policy_fingerprint=getattr(policy, "fingerprint", None),
                mesh=self.mesh,
                precision=self.precision.tier,
            ) or {}
        # constants of the AOT calling convention, hoisted off the hot path:
        # the flat (p1, p2) leaves (tuple flatten = concatenated child
        # flattens, so appending the per-request arrays reproduces the full
        # jit argument order) and the cost-of-capital scalar
        self._flat_params = jax.tree.leaves((self._p1, self._p2))
        self._coc = jnp.asarray(self.cost_of_capital, self._eval_dt)
        if self.mesh is not None:
            self._coc = jax.device_put(self._coc, self._rep)
        # XLA-compile baseline for THIS engine: `_eval_core`'s executable
        # cache is process-wide, so per-engine counts are deltas from here.
        # The counter rides a private jax attribute (_cache_size) — if a jax
        # upgrade drops it, serving must keep working and only the optional
        # introspection degrades (xla_compiles -> None)
        self._compiles0 = self._eval_core_compiles()

    @staticmethod
    def _eval_core_compiles() -> int | None:
        try:
            return compile_count(_eval_core)
        except TypeError:
            return None

    # -- cache introspection -------------------------------------------------

    def cache_info(self) -> dict:
        """Bucket-cache counters: each miss is the one compile its bucket
        ever pays; every later request of any size in that bucket is a hit.

        ``xla_compiles`` is the jit executable cache's growth since this
        engine was built (orp_tpu/lint/trace_audit.py). The cache is
        process-wide, so with a SINGLE live engine this is exactly its
        compile bill (at most one per bucket; less when an earlier engine
        with the same policy statics already paid one) — interleaved traffic
        on other engines inflates it. For a strict per-region audit, wrap
        the traffic in ``CompileAudit`` + ``watch_serve_engine``. None when
        the running jax exposes no executable-cache counter."""
        now = self._eval_core_compiles()
        return {
            "hits": self.hits,
            "misses": self.misses,
            "precision": self.precision.tier,
            "mesh_devices": 1 if self.mesh is None else int(self.mesh.devices.size),
            "buckets": sorted(self._buckets),
            "aot_buckets": sorted(self._aot),
            "aot_hits": self.aot_hits,
            "aot_circuit_open": self._breaker.open_keys,
            "xla_compiles": (
                now - self._compiles0
                if now is not None and self._compiles0 is not None else None
            ),
        }

    def program_cost(self, n_rows: int) -> dict:
        """FLOPs / bytes-accessed of the executable serving ``n_rows``-row
        requests (``cost_analysis`` on a fresh lower+compile of the bucket
        program from avals — no request data touched). The roofline join
        (``obs/perf.py``) divides these by measured device seconds. A
        profiling/bench helper, not a hot path: with the persistent compile
        cache on, the compile is a disk read after the first call."""
        from orp_tpu.aot.compile import cost_summary

        b = self.bucket_for(n_rows)
        dt = self._eval_dt
        sds = jax.ShapeDtypeStruct
        lowered = _eval_core.lower(
            self.model, self._p1, self._p2, sds((), jnp.int32),
            sds((b, self.model.n_features), dt),
            sds((b, self.n_instruments), dt), self._coc,
            dual_mode=self.dual_mode,
            holdings_combine=self.holdings_combine,
            precision=self.precision.tier,
        )
        return {"bucket": b, **cost_summary(lowered.compile())}

    # -- evaluation ----------------------------------------------------------

    def bucket_for(self, n_rows: int, mesh="engine") -> int:
        """The padded size requests of ``n_rows`` dispatch at: next
        power-of-two (floored at ``min_bucket``), then rounded up to a
        multiple of the mesh size so every shard is equal — a no-op for the
        power-of-two meshes real pods are, load-bearing for odd submeshes.
        ``mesh`` defaults to the engine's own; the AOT exporter passes each
        topology explicitly so bucket rounding cannot drift between export
        and serve."""
        from orp_tpu.parallel.mesh import pad_to_mesh

        if mesh == "engine":
            mesh = self.mesh
        b = pad_to_mesh(next_bucket(n_rows, min_bucket=self.min_bucket), mesh)
        if b > self.max_bucket:
            raise ValueError(
                f"batch of {n_rows} rows exceeds max_bucket={self.max_bucket}; "
                "split the request (or raise max_bucket)"
            )
        return b

    def evaluate(self, date_idx: int, states, prices=None):
        """Hedge the batch: ``(phi, psi, value)`` as host numpy arrays of
        ``len(states)`` rows.

        ``states``: ``(n, n_features)`` feature rows in the TRAINING
        normalisation (e.g. ``S_t/S0`` for the European policy).
        ``prices``: optional ``(n, k)`` hedge-instrument prices (risky legs
        then bond, same normalisation) — required for ``value``; without
        them ``value`` is returned as None (phi/psi need no prices).
        ``date_idx``: rebalance-date index ``0..n_dates-1``; negative
        indices count from the end like numpy.

        Blocking convenience over :meth:`evaluate_async` — same dispatch,
        same bits; a served result IS the deliverable, so the caller's
        clock stops only after the device finishes.
        """
        return self.evaluate_async(date_idx, states, prices).result()

    def evaluate_async(self, date_idx: int, states, prices=None) -> PendingEval:
        """Validate, pad and DISPATCH the batch without blocking on the
        device: returns a :class:`PendingEval` whose ``result()`` does the
        block + unpad. This is the continuous batcher's overlap point —
        batch N executes while batch N+1 is admitted and padded. Counters
        (bucket hits/misses, aot) record here, at successful dispatch, so
        a retried transient failure never inflates telemetry.
        """
        states = np.asarray(states)
        if states.ndim == 1:
            states = states[None, :]
        n, f = states.shape
        if f != self.model.n_features:
            raise ValueError(
                f"states have {f} features; this policy was trained on "
                f"{self.model.n_features}"
            )
        idx = int(date_idx)
        if not -self.n_dates <= idx < self.n_dates:
            raise IndexError(
                f"date_idx {date_idx} out of range for {self.n_dates} dates")
        idx %= self.n_dates
        has_prices = prices is not None
        k = self.n_instruments
        if has_prices:
            prices = np.asarray(prices)
            if prices.ndim == 1:
                prices = prices[None, :]
            if prices.shape != (n, k):
                raise ValueError(
                    f"prices shape {prices.shape} != {(n, k)} "
                    "(risky legs then bond, one row per state)"
                )
        if n == 0:
            # empty request: short-circuit BEFORE bucketing — an
            # all-padding bucket would bill a full device execute (and a
            # possible compile) for zero rows. No counters move: nothing
            # was dispatched.
            return self._empty_pending(has_prices)
        b = self.bucket_for(n)
        aot_ex = self._aot.get(b)
        # categorize now, RECORD after the dispatch succeeds: a failed
        # attempt that the batcher's guard policy retries must not inflate
        # the request/row counters (telemetry under degradation would
        # overstate traffic by one per retry)
        bucket_kind = ("hit" if b in self._buckets
                       else "aot_warm" if aot_ex is not None else "miss")
        dt = self._np_dt
        with span("serve/pad"):
            # block-shaped fast path: a request already AT its bucket size
            # in the serve dtype (the columnar ingest lane's usual shape —
            # blocks are sized to buckets) dispatches the caller's own
            # contiguous array, zero host copies. Inputs are read-only by
            # contract; a decoded wire frame arrives as exactly this shape
            if (n == b and states.dtype == dt
                    and states.flags["C_CONTIGUOUS"]):
                feats = states
            else:
                feats = np.zeros((b, f), dt)
                feats[:n] = states
            if (has_prices and n == b and prices.dtype == dt
                    and prices.flags["C_CONTIGUOUS"]):
                pr = prices
            else:
                pr = np.zeros((b, k), dt)
                if has_prices:
                    pr[:n] = prices
            if self.mesh is not None:
                # commit the padded rows shard-equal over the mesh here, so
                # the jit and AOT paths dispatch identical placements (and
                # the jit cache keys the topology into the executable)
                feats = jax.device_put(feats, self._rows)
                pr = jax.device_put(pr, self._rows)
        inj = _inject.active()
        with span("serve/dispatch", attrs={"bucket": b,
                                           "aot": aot_ex is not None}):
            if inj is not None:
                # chaos harness (orp_tpu/guard/inject.py): may sleep (slow
                # dependency) and/or raise a TransientDispatchError, which
                # propagates to the batcher's retry-with-backoff policy
                inj.fire("serve/dispatch", bucket=b)
            if aot_ex is not None:
                phi, psi, v = self._dispatch_aot(aot_ex, b, idx, feats, pr,
                                                 inj)
            else:
                phi, psi, v = self._jit_eval(idx, feats, pr)
        if bucket_kind == "hit":
            self.hits += 1
            # per-request counters are registry-only (sink_event=False): a
            # JSONL write per request would put sink-lock I/O inside the
            # latency every caller is timing. Totals still export via
            # metrics.prom; the RARE miss (once per bucket) keeps its event.
            obs_count("serve/bucket_hits", sink_event=False)
        elif bucket_kind == "aot_warm":
            # first touch of an AOT bucket compiles NOTHING (the executable
            # shipped in the bundle) — a hit, not a miss: `misses` stays the
            # engine's compile bill
            self.hits += 1
            self._buckets.add(b)
            obs_count("serve/bucket_aot_warm", bucket=str(b))
        else:
            self.misses += 1
            self._buckets.add(b)
            obs_count("serve/bucket_misses", bucket=str(b))
        obs_count("serve/rows", n, sink_event=False)
        if b > n:
            # first-class pad-waste accounting: the rows this dispatch
            # billed the device for but carried no request data (orp top's
            # pad-waste column; the ragged planner's objective)
            obs_count("serve/pad_waste_rows", b - n, sink_event=False)
        prof = _devprof.active()
        if prof is None:
            return PendingEval(phi, psi, v, n, has_prices, b)
        # attribution on: stamp the dispatch instant — the completion chain
        # in PendingEval.result attributes queue vs device seconds from it
        return PendingEval(phi, psi, v, n, has_prices, b, prof,
                           time.perf_counter())

    @staticmethod
    def _empty_pending(has_prices: bool) -> PendingEval:
        """The n=0 result: zero-row host arrays, bucket 0, no dispatch.
        ``PendingEval.result`` passes numpy through ``block_until_ready``
        unchanged, so the empty pending walks the normal result path."""
        z = np.zeros(0, np.float32)
        return PendingEval(z, z, z, 0, has_prices, 0)

    def evaluate_mixed_async(self, dates, states, prices=None) -> PendingEval:
        """Mixed-date dispatch: ``dates`` is one int per ROW, and the whole
        block executes as ONE device program (the Pallas mixed-date
        megakernel, serve/megakernel.py) instead of fragmenting into one
        bucketed dispatch per distinct date. f32 results are bitwise the
        loop-of-buckets path's (pinned in tests); counters mirror
        ``evaluate_async`` plus ``serve/megakernel_dispatches``."""
        if self.mesh is not None:
            raise ValueError(
                "mixed-date megakernel serves single-device engines; "
                "mesh engines keep the per-date bucketed path")
        states = np.asarray(states)
        if states.ndim == 1:
            states = states[None, :]
        n, f = states.shape
        if f != self.model.n_features:
            raise ValueError(
                f"states have {f} features; this policy was trained on "
                f"{self.model.n_features}"
            )
        dates = np.asarray(dates, np.int32).reshape(-1)
        if dates.shape[0] != n:
            raise ValueError(
                f"dates has {dates.shape[0]} entries for {n} rows "
                "(one rebalance-date index per row)")
        if n and not ((-self.n_dates <= dates) & (dates < self.n_dates)).all():
            raise IndexError(
                f"date indices out of range for {self.n_dates} dates")
        dates = dates % self.n_dates if n else dates
        has_prices = prices is not None
        k = self.n_instruments
        if has_prices:
            prices = np.asarray(prices)
            if prices.ndim == 1:
                prices = prices[None, :]
            if prices.shape != (n, k):
                raise ValueError(
                    f"prices shape {prices.shape} != {(n, k)} "
                    "(risky legs then bond, one row per state)"
                )
        if n == 0:
            return self._empty_pending(has_prices)
        b = self.bucket_for(n)
        hit = b in self._mixed_buckets
        dt = self._np_dt
        with span("serve/pad"):
            feats = np.zeros((b, f), dt)
            feats[:n] = states
            pr = np.zeros((b, k), dt)
            if has_prices:
                pr[:n] = prices
            dcol = np.zeros(b, np.int32)
            dcol[:n] = dates  # padded rows gather date 0: discarded at unpad
        with span("serve/dispatch", attrs={"bucket": b, "mixed": True}):
            phi, psi, v = self._mixed_eval(dcol, feats, pr)
        if hit:
            self.hits += 1
            obs_count("serve/bucket_hits", sink_event=False)
        else:
            self.misses += 1
            self._mixed_buckets.add(b)
            obs_count("serve/bucket_misses", bucket=str(b), mixed="1")
        obs_count("serve/rows", n, sink_event=False)
        obs_count("serve/megakernel_dispatches", sink_event=False)
        if b > n:
            obs_count("serve/pad_waste_rows", b - n, sink_event=False)
        prof = _devprof.active()
        if prof is None:
            return PendingEval(phi, psi, v, n, has_prices, b)
        return PendingEval(phi, psi, v, n, has_prices, b, prof,
                           time.perf_counter())

    def _mixed_eval(self, dates, feats, pr):
        """One fused mixed-date dispatch (lazy import: the megakernel pulls
        jax.experimental.pallas, which bucketed-only servers never pay)."""
        from orp_tpu.serve.megakernel import _eval_core_mixed, use_interpret

        return _eval_core_mixed(
            self.model, self._p1, self._p2,
            jnp.asarray(dates, jnp.int32),
            jnp.asarray(feats, self._eval_dt),
            jnp.asarray(pr, self._eval_dt), self._coc,
            dual_mode=self.dual_mode,
            holdings_combine=self.holdings_combine,
            precision=self.precision.tier,
            interpret=use_interpret(),
        )

    def _jit_eval(self, idx: int, feats, pr):
        """The always-correct jit path: one bucket-shaped ``_eval_core``
        dispatch (compiles on the bucket's first jit touch)."""
        return _eval_core(
            self.model, self._p1, self._p2, jnp.asarray(idx, jnp.int32),
            jnp.asarray(feats, self._eval_dt),
            jnp.asarray(pr, self._eval_dt), self._coc,
            dual_mode=self.dual_mode,
            holdings_combine=self.holdings_combine,
            precision=self.precision.tier,
        )

    def _dispatch_aot(self, aot_ex, b: int, idx: int, feats, pr, inj):
        """Execute bucket ``b``'s serialized executable; any failure falls
        back to the jit path for THIS request (same program, bitwise-equal
        results) and feeds the circuit breaker — ``aot_failure_threshold``
        consecutive failures open the circuit and demote the bucket to jit
        for the process lifetime (``guard/circuit_open``)."""
        try:
            if inj is not None:
                inj.fire("serve/aot_dispatch", bucket=b)
            if hasattr(aot_ex, "call_flat"):
                # pjrt codec: exact jit argument order (pre-flattened params
                # + the per-request arrays), pruned to the inputs XLA kept —
                # the same program the jit path would compile, minus the
                # compile
                flat = [*self._flat_params, jnp.asarray(idx, jnp.int32),
                        jnp.asarray(feats, self._eval_dt),
                        jnp.asarray(pr, self._eval_dt), self._coc]
                out = aot_ex.call_flat(flat)
            else:
                # pickle codec (mesh topologies): a sharding-aware Compiled
                # taking the dynamic jit arguments structured, exactly as
                # _jit_eval would pass them
                out = aot_ex.compiled(
                    self._p1, self._p2, jnp.asarray(idx, jnp.int32),
                    jnp.asarray(feats, self._eval_dt),
                    jnp.asarray(pr, self._eval_dt), self._coc)
        except Exception as e:  # noqa: BLE001 — counted, breakered, fallen back
            obs_count("guard/aot_exec_failure", bucket=str(b))
            if self._breaker.record_failure(b):
                self._aot.pop(b, None)
                warnings.warn(
                    f"AOT executable for bucket {b} failed "
                    f"{self._breaker.threshold} consecutive times "
                    f"({type(e).__name__}: {e}); circuit opened — bucket "
                    "demoted to the jit path for this process",
                    stacklevel=3,
                )
            return self._jit_eval(idx, feats, pr)
        self.aot_hits += 1
        self._breaker.record_success(b)
        return out

    def watchdog_trip(self, bucket) -> None:
        """A stuck-dispatch watchdog (``serve/health.py``) force-failed a
        hung batch in ``bucket``: count it against the SAME circuit breaker
        an execution failure feeds — a bucket whose serialized executable
        hangs repeatedly is as demoted as one that raises repeatedly
        (``guard/circuit_open``; jit for the process lifetime). Hangs keep
        their OWN streak key (``hang:<bucket>``): a hang surfaces at BLOCK
        time after a successful dispatch, so the dispatch-time
        ``record_success`` would otherwise wipe the streak between two
        consecutive hangs and the circuit could never open. A hang on a
        jit bucket still counts (honest telemetry) but there is nothing to
        demote."""
        obs_count("guard/aot_exec_failure", bucket=str(bucket), kind="hang")
        if self._breaker.record_failure(f"hang:{bucket}"):
            self._aot.pop(bucket, None)
            warnings.warn(
                f"bucket {bucket} exceeded the dispatch hard wall "
                f"{self._breaker.threshold} consecutive times; circuit "
                "opened — bucket demoted to the jit path for this process",
                stacklevel=3,
            )

    def watchdog_ok(self, bucket) -> None:
        """The watchdog saw this bucket's block complete inside the wall:
        break any hang streak — flakes never accumulate into a demotion,
        the same contract ``record_success`` gives execution failures."""
        self._breaker.record_success(f"hang:{bucket}")

    def prewarm(self, sizes) -> dict:
        """Pre-touch every bucket the given request sizes land in, so no
        live request ever pays first-touch cost: on a jit engine each
        bucket's one compile happens HERE (populating the persistent cache
        when ``orp_tpu.aot.enable_persistent_cache`` is active), on an AOT
        engine this is a cheap executable shakeout. Returns ``cache_info()``
        — after a prewarm covering the traffic's sizes, ``misses`` stops
        moving for good."""
        dt = self._np_dt
        # dedupe by TARGET bucket but evaluate the requested row count: on a
        # non-power-of-two mesh the padded bucket is itself not a bucket
        # boundary (bucket_for(18) == 33 on a 3-mesh), so evaluating b rows
        # would warm the wrong executable and leave the live size cold
        by_bucket = {}
        for n in sizes:
            by_bucket.setdefault(self.bucket_for(int(n)), int(n))
        for _, n in sorted(by_bucket.items()):
            self.evaluate(0, np.ones((n, self.model.n_features), dt))
        return self.cache_info()
