"""``ResilientGatewayClient``: delivery-guaranteed producer for the ingest
plane.

The plain :class:`~orp_tpu.serve.gateway.GatewayClient` is one socket and
one in-flight frame: if the connection drops between send and reply the
caller cannot know whether its rows were served. This client closes that
gap with the ``orp-ingest-v2`` delivery machinery (``serve/wire.py``):

- every REQUEST frame carries a per-session monotonically increasing
  ``seq`` and stays in a **bounded replay buffer** until its reply (ack)
  arrives — ``window`` unacknowledged frames is also the client-side
  backpressure bound: ``submit_block`` blocks when the buffer is full;
- on ANY connection loss the client **reconnects with exponential backoff
  off the guard retry machinery** (:class:`~orp_tpu.guard.GuardPolicy`'s
  ``backoff_s`` schedule), RESUMEs its session token with a HELLO
  handshake and **replays** every unacknowledged frame in order. The
  gateway's per-session dedup window makes this at-least-once-submit /
  exactly-once-serve: an already-served frame is re-answered from the
  reply cache, an in-flight one is adopted, only genuinely new frames
  dispatch;
- a **BUSY** frame (gateway backpressure) schedules the named frame for
  retransmit after a backoff — the producer slows down, no rows died;
- a **REDIRECT** frame (drain-and-redirect handoff) marks the named frame
  for replay against the successor; the client keeps the old connection
  until every ADMITTED frame's reply has flushed, then reconnects to the
  successor and replays the refused ones — zero rows lost across the
  handoff.

One background reader thread owns every socket read (replies, handshakes,
reconnects); ``submit_block``/``submit_block_async`` run on the caller's
thread. The README quickstart::

    from orp_tpu.serve.client import ResilientGatewayClient
    with ResilientGatewayClient("127.0.0.1", 7433) as c:
        futs = [c.submit_block_async("desk-a", 0, blk) for blk in blocks]
        results = [f.result(timeout=30) for f in futs]
    # a dropped connection, BUSY spell or gateway handoff in between is
    # absorbed: every block resolves exactly once, bitwise what an
    # uninterrupted run serves
"""

from __future__ import annotations

import collections
import socket
import threading
import time

from orp_tpu.guard import inject
from orp_tpu.guard.serve import GuardPolicy
from orp_tpu.obs import count as obs_count
from orp_tpu.obs import flight
from orp_tpu.serve import wire
from orp_tpu.serve.batcher import SlimFuture
from orp_tpu.serve.gateway import (MAX_FRAME_BYTES, GatewayError, _LEN,
                                   _recv_frame)

#: default reconnect schedule: 29 attempts, 50ms doubling to a 2s cap —
#: ~55s total budget, sized to survive a REAL supervisor restart of an
#: `orp serve-gateway` process (jax import + bundle load take tens of
#: seconds cold; a 2s budget only ever survived in-process restarts).
#: A producer that wants fail-fast passes its own GuardPolicy.
DEFAULT_RETRY = GuardPolicy(max_retries=29, backoff_ms=50.0,
                            backoff_cap_ms=2000.0)


def _tx(sock: socket.socket, data: bytes) -> None:
    sock.sendall(data)  # orp: noqa[ORP014] -- every socket entering this helper was settimeout'd at _open


class _Entry:
    """One unacknowledged frame: the encoded bytes (the replay buffer IS
    the frames — nothing is re-encoded), its future, and its retransmit
    state."""

    __slots__ = ("seq", "frame", "future", "due", "busy_n", "redirected",
                 "sent_at")

    def __init__(self, seq: int, frame: bytes):
        self.seq = seq
        self.frame = frame
        self.future = SlimFuture()
        self.due = None          # perf_counter instant of a BUSY retransmit
        self.busy_n = 0
        self.redirected = False  # refused by a draining gateway: replay
        self.sent_at = time.perf_counter()


class ResilientGatewayClient:
    """Reconnect-replay producer over the ``orp-ingest-v2`` wire.

    ``window``     — replay-buffer bound = max unacknowledged frames in
    flight; ``submit_block`` blocks (client-side backpressure) when full.
    ``retry``      — the reconnect :class:`~orp_tpu.guard.GuardPolicy`:
    ``max_retries`` connection attempts per outage, ``backoff_s`` schedule
    between them (also the BUSY retransmit schedule). Budget exhausted =
    every outstanding future fails with :class:`GatewayError` and the
    client is dead.
    ``timeout_s``  — connect timeout, mid-reply stall deadline, and the
    default ``submit_block`` result bound.

    ``stats`` counts ``reconnects``/``replayed_frames``/``busy``/
    ``redirects``/``duplicate_replies`` — the drill's evidence that
    exactly-once-serve held (``duplicate_replies`` stays 0).
    """

    def __init__(self, addr: str, port: int, *, window: int = 8,
                 retry: GuardPolicy = DEFAULT_RETRY,
                 timeout_s: float = 30.0,
                 max_frame_bytes: int = MAX_FRAME_BYTES):
        if window < 1:
            raise ValueError(f"window={window} must be >= 1")
        self._target = (str(addr), int(port))
        self._retry = retry
        self.timeout_s = float(timeout_s)
        self._window = int(window)
        self._max_frame_bytes = int(max_frame_bytes)
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)
        self._unacked: collections.OrderedDict[int, _Entry] = \
            collections.OrderedDict()
        self._next_seq = 1
        self._token = b""
        self._sock: socket.socket | None = None
        # connection generation: bumped by every reconnect. A producer-side
        # send is only valid for the generation its entry was queued under —
        # past it, the reconnect's replay owns the frame (sending it again
        # would deliver the same seq twice on one connection)
        self._gen = 0
        self._send_lock = threading.Lock()
        self._closed = False
        self._dead: Exception | None = None
        self._redirect: tuple[str, int] | None = None
        self._interrupt = threading.Event()
        self._pong = threading.Event()
        self.stats = {"reconnects": 0, "replayed_frames": 0, "busy": 0,
                      "redirects": 0, "duplicate_replies": 0}
        # connect in the constructor (fail fast on a wrong address); every
        # LATER outage is the reader thread's to absorb
        sock = self._open(self._target)
        with self._lock:
            self._sock = sock
        self._reader = threading.Thread(
            target=self._read_loop, name="orp-gateway-client", daemon=True)
        self._reader.start()

    # -- producer side -------------------------------------------------------

    @property
    def dead(self) -> bool:
        """True once the client is unusable: closed, or its reconnect
        budget exhausted (every submit raises). A fleet router polls this
        to decide whether a fresh client is needed for the replica."""
        with self._lock:
            return self._closed or self._dead is not None

    def submit_block_async(self, tenant: str, date_idx: int, states,
                           prices=None, deadlines=None, *,
                           deadline_ms: float | None = None,
                           trace=None) -> SlimFuture:
        """Enqueue one block; the future resolves to its
        :class:`~orp_tpu.serve.ingest.BlockResult` exactly once — across
        reconnects, replays, BUSY spells and gateway handoffs — or raises
        :class:`GatewayError` when the gateway refused the frame or the
        reconnect budget died. Blocks while the replay buffer is full (the
        client-side backpressure bound).

        ``trace``: an optional ``(trace_id, parent_span)`` pair
        (``obs.new_trace()``) stamped into the frame's trace extension.
        The replay buffer keeps the encoded bytes, so a replayed frame
        carries the SAME trace context — one trace id spans the frame's
        whole delivery story, reconnects included — and the resolved
        ``BlockResult.timing`` carries the gateway's server-timing pair."""
        with self._space:
            if self._closed:
                raise RuntimeError("ResilientGatewayClient is closed")
            if self._dead is not None:
                raise self._dead
            while len(self._unacked) >= self._window:
                self._space.wait(timeout=0.05)
                if self._closed:
                    raise RuntimeError("ResilientGatewayClient is closed")
                if self._dead is not None:
                    raise self._dead
            seq = self._next_seq
            self._next_seq += 1
        # encode OUTSIDE the lock: a multi-MB block's column copy must not
        # stall the reader's ack processing (with concurrent producer
        # threads the window may overshoot by at most threads-1 — the
        # buffer bound is per-producer-tight, not global-exact)
        frame = wire.encode_request(tenant, date_idx, states, prices,
                                    deadlines, deadline_ms=deadline_ms,
                                    seq=seq, trace=trace)
        e = _Entry(seq, frame)
        with self._space:
            if self._closed:
                raise RuntimeError("ResilientGatewayClient is closed")
            self._unacked[seq] = e
            gen = self._gen
        self._send_entry(e, gen)
        return e.future

    def submit_block(self, tenant: str, date_idx: int, states, prices=None,
                     deadlines=None, *, deadline_ms: float | None = None,
                     timeout_s: float | None = None, trace=None):
        """Synchronous convenience: ``submit_block_async(...).result()``."""
        fut = self.submit_block_async(tenant, date_idx, states, prices,
                                      deadlines, deadline_ms=deadline_ms,
                                      trace=trace)
        return fut.result(timeout=self.timeout_s if timeout_s is None
                          else timeout_s)

    def ping(self, timeout_s: float = 5.0) -> bool:
        """One PING round trip through the live connection."""
        self._pong.clear()
        self._send(wire.encode_ping())
        return self._pong.wait(timeout_s)

    def close(self) -> None:
        with self._space:
            if self._closed:
                return
            self._closed = True
            entries = list(self._unacked.values())
            self._unacked.clear()
            self._space.notify_all()
            sock, self._sock = self._sock, None
        self._interrupt.set()
        if sock is not None:
            try:
                sock.close()
            except OSError:  # orp: noqa[ORP009] -- best-effort close; nothing to emit
                pass
        self._reader.join(5.0)
        err = GatewayError("client closed with the frame unacknowledged")
        for e in entries:
            if e.future.set_running_or_notify_cancel() and not e.future.done():
                e.future.set_exception(err)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- socket plumbing -----------------------------------------------------

    def _open(self, target) -> socket.socket:
        """One connect + HELLO/RESUME handshake; raises OSError/WireError
        on failure (the reconnect loop's retry unit)."""
        sock = socket.create_connection(target, timeout=self.timeout_s)
        sock.settimeout(0.05)  # the reader's housekeeping poll
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            hello = wire.encode_hello(self._token)
            sock.sendall(_LEN.pack(len(hello)) + hello)
            # bound the WHOLE handshake, not just a started frame: the
            # frame deadline only arms at the first byte, and a
            # dead-but-accepting endpoint sends none — without this wall
            # the constructor (and every reconnect attempt) hangs forever
            t0 = time.perf_counter()

            def handshake_wall():
                self._check_interrupt()
                if time.perf_counter() - t0 > self.timeout_s:
                    raise OSError(  # orp: noqa[ORP016] -- the reconnect loop that catches this counts client/reconnects + flight-records the failure with its wall
                        f"no WELCOME within {self.timeout_s}s — the "
                        "endpoint accepts connections but does not speak "
                        "orp-ingest (dead-but-accepting)")

            reply = _recv_frame(sock, None, self._max_frame_bytes,
                                deadline_s=self.timeout_s,
                                idle=handshake_wall)
            if reply is None:
                raise OSError("connection closed during the HELLO handshake")
            kind = wire.decode_kind(reply)
            if kind == wire.KIND_REDIRECT:
                host, port, _ = wire.decode_redirect(reply)
                with self._lock:
                    self._redirect = (host, port)
                raise OSError(f"gateway is draining; redirected to "
                              f"{host}:{port}")
            token, last_seq = wire.decode_welcome(reply)
            self._token = token
            obs_count("serve/client_sessions", sink_event=False)
            return sock
        except BaseException:
            try:
                sock.close()
            except OSError:  # orp: noqa[ORP009] -- the handshake failure is re-raised; the close is best effort
                pass
            raise

    def _check_interrupt(self) -> None:
        if self._interrupt.is_set():
            raise OSError("client closing")

    def _send(self, frame: bytes) -> None:
        """Best-effort transmit of an UNSEQUENCED frame (ping): a failure
        just pokes the reader."""
        with self._lock:
            sock = self._sock
        if sock is None:
            return  # an outage is in progress; the reconnect replays
        try:
            self._send_raw(sock, frame)
        except OSError:
            self._drop_sock(sock)

    def _send_entry(self, e: _Entry, gen: int) -> None:
        """Transmit a buffered frame only while the connection generation
        it was queued under is still current. A reconnect in the window
        between queueing and sending means the replay loop owns this frame
        (its snapshot included the entry) — sending it here too would put
        the same seq on the new connection twice and the second reply
        would count as a duplicate."""
        with self._lock:
            if self._gen != gen or self._sock is None:
                return  # superseded: the reconnect replay delivers it
            sock = self._sock
        try:
            self._send_raw(sock, e.frame)
        except OSError:
            self._drop_sock(sock)

    def _send_raw(self, sock: socket.socket, frame: bytes) -> None:
        data = _LEN.pack(len(frame)) + frame
        inj = inject.active()
        if inj is not None:
            hold = inj.stall_send("client/send")
            if hold is not None:
                # the stalled-reader fault: half a frame, then silence with
                # the socket OPEN — the gateway's frame deadline must evict
                with self._send_lock:
                    _tx(sock, data[:max(1, len(data) // 2)])
                time.sleep(hold)
                raise OSError("injected stalled send (gateway should have "
                              "evicted this connection)")
            if inj.torn_send("client/send"):
                # the torn-frame fault: half a frame, then a dead socket —
                # the gateway discards the partial, the replay re-delivers
                with self._send_lock:
                    _tx(sock, data[:max(1, len(data) // 2)])
                sock.close()
                raise OSError("injected torn frame")
        with self._send_lock:
            _tx(sock, data)

    def _drop_sock(self, sock) -> None:
        """Retire a dead socket; the reader notices and reconnects."""
        with self._lock:
            if self._sock is sock:
                self._sock = None
        try:
            sock.close()
        except OSError:  # orp: noqa[ORP009] -- already dead; the reconnect is the response
            pass

    # -- reader thread -------------------------------------------------------

    def _read_loop(self) -> None:
        """The one thread that reads: replies, handshakes, reconnects. Its
        poll ticks (``idle``) also run the BUSY retransmit schedule."""
        while True:
            with self._lock:
                if self._closed:
                    return
                sock = self._sock
            if sock is None:
                if not self._reconnect():
                    return
                continue
            try:
                frame = _recv_frame(sock, None, self._max_frame_bytes,
                                    deadline_s=self.timeout_s,
                                    idle=self._housekeep)
            except (OSError, wire.WireError):
                # mid-reply stall, reset, or garbage: the connection is
                # unusable — reconnect and replay
                self._drop_sock(sock)
                continue
            if frame is None:
                self._drop_sock(sock)
                continue
            try:
                self._on_frame(frame)
            except wire.WireError:
                self._drop_sock(sock)

    def _on_frame(self, frame: bytes) -> None:
        kind, seq = wire.frame_meta(frame)
        if kind == wire.KIND_PONG:
            self._pong.set()
            return
        if kind == wire.KIND_BUSY:
            self.stats["busy"] += 1
            obs_count("serve/client_busy")
            with self._lock:
                e = self._unacked.get(seq)
                if e is not None:
                    e.busy_n += 1
                    e.due = time.perf_counter() + \
                        self._retry.backoff_s(min(e.busy_n, 8))
            return
        if kind == wire.KIND_REDIRECT:
            host, port, seq = wire.decode_redirect(frame)
            self.stats["redirects"] += 1
            obs_count("serve/client_redirects")
            with self._lock:
                self._redirect = (host, port)
                if seq:
                    e = self._unacked.get(seq)
                    if e is not None:
                        e.redirected = True
            self._maybe_follow_redirect()
            return
        if kind not in (wire.KIND_REPLY, wire.KIND_ERROR):
            return  # WELCOME out of band etc.: nothing to correlate
        if seq == 0:
            # a connection-level (seq-less) ERROR means the gateway could
            # not even attribute the failure to a frame — the stream is
            # not trustworthy. Treat it as poison: raise so the read loop
            # drops the socket and the reconnect replays every unacked
            # frame (waiting for a reset that may never come would leak
            # the frames' window slots forever)
            obs_count("serve/client_conn_errors")
            raise wire.WireError(
                "connection-level ERROR from the gateway: "
                + (wire.decode_error(frame) if kind == wire.KIND_ERROR
                   else "unsequenced reply"))
        # decode BEFORE popping from the replay buffer: a corrupt reply
        # raises WireError to the read loop (drop + reconnect) with the
        # frame STILL buffered — popping first would lose it forever
        if kind == wire.KIND_ERROR:
            outcome_err = GatewayError(wire.decode_error(frame))
            outcome = None
        else:
            outcome_err = None
            outcome = wire.decode_reply(frame)
        with self._space:
            e = self._unacked.pop(seq, None)
            self._space.notify_all()
        if e is None:
            # an ack for a frame we no longer track (e.g. the reply raced a
            # retransmit): MUST stay 0 in the exactly-once drill
            self.stats["duplicate_replies"] += 1
            obs_count("serve/client_duplicate_replies")
            return
        if e.future.set_running_or_notify_cancel():
            if outcome_err is not None:
                e.future.set_exception(outcome_err)
            else:
                e.future.set_result(outcome)
        self._maybe_follow_redirect()

    def _housekeep(self) -> None:
        """Reader poll tick: retransmit BUSY-deferred frames whose backoff
        elapsed (the producer slowing down, as told)."""
        if self._interrupt.is_set():
            raise OSError("client closing")
        now = time.perf_counter()
        with self._lock:
            due = [e for e in self._unacked.values()
                   if e.due is not None and e.due <= now]
            for e in due:
                e.due = None
            gen = self._gen
        for e in due:
            self._send_entry(e, gen)

    def _maybe_follow_redirect(self) -> None:
        """Drain-and-redirect: once every still-unacked frame has been
        REDIRECTed (the admitted ones' replies all flushed), drop the old
        connection — the reconnect targets the successor and replays."""
        with self._lock:
            if self._redirect is None or self._sock is None:
                return
            if not all(e.redirected for e in self._unacked.values()):
                return  # admitted frames still owe replies on this socket
            sock, self._sock = self._sock, None
        try:
            sock.close()
        except OSError:  # orp: noqa[ORP009] -- handing off; the successor connect is the response
            pass

    def _reconnect(self) -> bool:
        """Exponential-backoff reconnect + RESUME + replay — the guard
        retry schedule applied to the connection itself. Returns False when
        the client is dead (budget exhausted or closed)."""
        pol = self._retry
        attempts = 1 + pol.max_retries
        last: Exception | None = None
        for attempt in range(1, attempts + 1):
            with self._lock:
                if self._closed:
                    return False
                target = self._redirect or self._target
            try:
                sock = self._open(target)
            except (OSError, wire.WireError) as e:
                last = e
                if attempt < attempts:
                    obs_count("guard/retry", site="client/connect",
                              attempt=str(attempt))
                    self._interrupt.wait(pol.backoff_s(attempt))
                continue
            with self._space:
                self._target = target
                self._redirect = None
                self._sock = sock
                # new generation: any in-flight producer send queued under
                # the old one stands down — the snapshot below owns delivery
                self._gen += 1
                entries = list(self._unacked.values())
                for e in entries:
                    e.redirected = False
                    e.due = None
            self.stats["reconnects"] += 1
            self.stats["replayed_frames"] += len(entries)
            obs_count("serve/client_reconnects")
            flight.record("reconnect", attempt=attempt,
                          target=f"{target[0]}:{target[1]}",
                          replayed=len(entries))
            # replay in seq order: the session window admits them in order,
            # answering already-served ones from the reply cache
            for e in entries:
                try:
                    self._send_raw(sock, e.frame)
                except OSError:
                    self._drop_sock(sock)
                    break  # next loop iteration reconnects again
            return True
        flight.record("client_dead", attempts=attempts,
                      target=f"{self._target[0]}:{self._target[1]}")
        dead = GatewayError(
            f"reconnect budget exhausted after {attempts} attempts to "
            f"{self._target[0]}:{self._target[1]}: {last}")
        with self._space:
            self._dead = dead
            entries = list(self._unacked.values())
            self._unacked.clear()
            self._space.notify_all()
        for e in entries:
            if e.future.set_running_or_notify_cancel():
                e.future.set_exception(dead)
        return False
