"""Horizontal serve fleet: N gateways fanning frames out to M replicas.

One ``orp serve-gateway`` process fronting one ``ServeHost`` serves one
box. "Millions of users" is a FLEET: many gateway processes, many serve
replicas, one consistent view of which tenant lives where. This module is
that routing layer, built from parts the previous rounds already proved:

- **deterministic tenant→replica routing** — rendezvous (highest-random-
  weight) hashing over a salt-free keyed digest (:func:`route_weight`,
  ``hashlib.blake2b``): every gateway process computes the IDENTICAL
  mapping from the same replica set, with no coordination, no shared
  state and no per-process hash salting (builtin ``hash()`` is salted per
  process — lint rule ORP018 exists because using it here silently splits
  the fleet's routing view). When a replica drops out, ONLY its tenants
  move (the rendezvous property); everyone else's mapping is untouched.
- **health-driven remapping** — :class:`ReplicaHealth` consumes the
  existing PR 12 signals (the HEALTH wire kind every gateway already
  answers, draining flag included); a replica that stops answering (or
  reports draining) leaves the healthy set and its tenants remap on the
  next table read. No side-channel probe protocol: the health plane the
  fleet routes on is the one the operator already scrapes (the Dapper
  discipline — route on the always-on trace/health plane, PAPERS.md).
- **forwarding over the delivery substrate** — :class:`FleetHost` wears
  the ``ServeHost`` submit surface (``submit_block`` → one future), so
  the EXISTING :class:`~orp_tpu.serve.gateway.ServeGateway` fronts it
  unchanged: producers keep their v2 sessions, dedup windows, BUSY
  backpressure and drain-and-redirect against the gateway, while each
  block is forwarded to its mapped replica over a per-replica
  :class:`~orp_tpu.serve.client.ResilientGatewayClient` — the PR 11
  reconnect-replay machinery IS the fleet's loss model. A transient
  replica blip is absorbed by that client (reconnect + RESUME + replay,
  exactly-once-serve); a replica DEATH exhausts its fast reconnect
  budget, the replica is marked suspect, and the pending blocks re-route
  to the rendezvous successor — no new loss semantics, the same replay
  buffer and dedup window doing the same job one hop deeper.

The routing-table core (``ReplicaSpec``/``RoutingTable``/
``load_topology``/``fleet_snapshot``) is deliberately stdlib-only and
import-light: ``tests/test_fleet.py`` loads THIS FILE standalone in
subprocesses (different ``PYTHONHASHSEED``) to pin that two gateway
processes agree on every mapping — the property the whole fleet stands
on. Everything that needs the serve plane imports it lazily.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import pathlib
import threading
import time

#: deterministic tenant sample every gateway answers the same way — the
#: ``orp doctor --fleet`` routing-agreement probe's common ground
ROUTE_SAMPLE = tuple(f"tenant-{i:02d}" for i in range(16))


class FleetError(RuntimeError):
    """A fleet-level routing/forwarding failure (the message is flag-speak)."""


class NoHealthyReplica(FleetError):
    """Every replica is out of the healthy set — nothing can take the
    tenant. The caller's future fails loudly; nothing is silently queued."""


@dataclasses.dataclass(frozen=True)
class ReplicaSpec:
    """One serve replica: a name (the routing identity — STABLE across
    restarts, or its tenants migrate) and the host:port of its
    ``orp serve-gateway`` ingest front."""

    name: str
    addr: str
    port: int

    @property
    def address(self) -> tuple[str, int]:
        return (self.addr, int(self.port))

    @staticmethod
    def parse(name: str, target: str) -> "ReplicaSpec":
        host, _, port = str(target).rpartition(":")
        if not host or not port.isdigit():
            raise FleetError(
                f"replica {name!r} names {target!r}; expected host:port of "
                "its serve-gateway ingest front")
        return ReplicaSpec(str(name), host, int(port))


def route_weight(tenant: str, replica: str) -> int:
    """The rendezvous weight of ``(tenant, replica)``: a salt-free keyed
    digest (blake2b-64), identical in every process on every box. Builtin
    ``hash()`` is per-process salted (PYTHONHASHSEED) and would give every
    gateway its OWN routing table — the exact failure ORP018 lints for."""
    h = hashlib.blake2b(f"{tenant}|{replica}".encode("utf-8"), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class RoutingTable:
    """The fleet's tenant→replica mapping: rendezvous hashing over the
    HEALTHY replicas. Pure and deterministic — two gateways holding the
    same ``(replicas, healthy)`` view compute identical mappings with no
    coordination, and a replica leaving the healthy set moves ONLY its own
    tenants (each remaps to its rendezvous runner-up)."""

    def __init__(self, replicas, healthy=None):
        reps = tuple(sorted(replicas, key=lambda r: r.name))
        names = [r.name for r in reps]
        if len(set(names)) != len(names):
            raise FleetError(f"duplicate replica names in {names} — the "
                             "routing identity must be unique")
        self.replicas = reps
        self.healthy = (frozenset(names) if healthy is None
                        else frozenset(healthy) & frozenset(names))
        self._by_name = {r.name: r for r in reps}

    def replica_for(self, tenant: str, exclude=()) -> ReplicaSpec:
        """The replica serving ``tenant``: highest rendezvous weight among
        healthy replicas (ties broken by name — total order, no salt).
        ``exclude``: replica names additionally struck for THIS decision
        (the re-route path's just-observed-dead set, ahead of the health
        monitor catching up)."""
        candidates = [r for r in self.replicas
                      if r.name in self.healthy and r.name not in exclude]
        if not candidates:
            raise NoHealthyReplica(
                f"no healthy replica for tenant {tenant!r} "
                f"(replicas {[r.name for r in self.replicas]}, healthy "
                f"{sorted(self.healthy)}, excluded {sorted(exclude)}) — "
                "start replicas or fix their health probes")
        return max(candidates,
                   key=lambda r: (route_weight(tenant, r.name), r.name))

    def mapping(self, tenants) -> dict[str, str]:
        """``{tenant: replica_name}`` for a tenant sample — what the doctor
        compares across gateway processes."""
        return {t: self.replica_for(t).name for t in tenants}

    def assigned(self, tenants, replica: str) -> list:
        """The subset of ``tenants`` this table maps to ``replica`` — a
        replica's predictive-prefetch working set. Because the assignment
        is pure rendezvous, the replica can compute its OWN set from the
        shared topology view with no coordination; feed it to
        ``ServeHost.prefetch`` (see ``orp_tpu.store.tier
        .prefetch_assigned``) on bring-up and from
        ``ReplicaHealth.on_change``, so a remap warms the newly-landed
        tenants before their rerouted first request arrives."""
        return [t for t in tenants
                if self.replica_for(t).name == str(replica)]

    def version(self) -> str:
        """Fingerprint of the routing view (replica set + healthy set):
        gateways agreeing on the version agree on every mapping."""
        basis = "|".join(f"{r.name}@{r.addr}:{r.port}" for r in self.replicas)
        basis += "||" + ",".join(sorted(self.healthy))
        return hashlib.sha256(basis.encode("utf-8")).hexdigest()[:12]

    def with_health(self, healthy) -> "RoutingTable":
        return RoutingTable(self.replicas, healthy)


def load_topology(path) -> dict:
    """Parse a fleet ``topology.json``::

        {"gateways": ["127.0.0.1:7433", "127.0.0.1:7434"],
         "replicas": {"r0": "127.0.0.1:7500", "r1": "127.0.0.1:7501"}}

    Returns ``{"gateways": [(addr, port), ...], "replicas":
    [ReplicaSpec, ...]}``. Malformations refuse in flag-speak."""
    p = pathlib.Path(path)
    try:
        doc = json.loads(p.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise FleetError(f"topology {p}: {e} — expected a JSON object with "
                         '"gateways" and "replicas"') from None
    if not isinstance(doc, dict) or not isinstance(doc.get("replicas"), dict):
        raise FleetError(
            f'topology {p}: needs a "replicas" object mapping name -> '
            '"host:port" (and optionally a "gateways" list)')
    replicas = [ReplicaSpec.parse(n, t)
                for n, t in sorted(doc["replicas"].items())]
    gateways = []
    for g in doc.get("gateways", ()):
        host, _, port = str(g).rpartition(":")
        if not host or not port.isdigit():
            raise FleetError(f"topology {p}: gateway {g!r} is not host:port")
        gateways.append((host, int(port)))
    if not replicas:
        raise FleetError(f"topology {p}: zero replicas — nothing to route to")
    return {"gateways": gateways, "replicas": replicas}


class ReplicaHealth:
    """The fleet's health view, fed by the PR 12 scrape plane: a poller
    thread sends each replica the HEALTH wire kind (the same probe ``orp
    top``/``orp doctor --metrics`` use) and keeps a healthy set + per-
    replica health age. A replica is unhealthy after ``fail_after``
    consecutive probe failures, or immediately when it reports
    ``draining`` (its own gateway is already redirecting), or when the
    forwarding path calls :meth:`mark_suspect` (a failed forward is a
    health signal the next probe confirms or clears).

    ``on_change(healthy_set)`` fires OUTSIDE the lock whenever the healthy
    set changes — the FleetHost's remap trigger."""

    def __init__(self, replicas, *, poll_s: float = 1.0,
                 timeout_s: float = 2.0, fail_after: int = 2,
                 on_change=None, start: bool = True):
        self.replicas = tuple(sorted(replicas, key=lambda r: r.name))
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.fail_after = max(1, int(fail_after))
        self.on_change = on_change
        self._lock = threading.Lock()
        self._fails = {r.name: 0 for r in self.replicas}
        self._last_ok = {r.name: None for r in self.replicas}
        self._healthy = frozenset(r.name for r in self.replicas)
        self._closed = threading.Event()
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._poll_loop, name="orp-fleet-health", daemon=True)
            self._thread.start()

    # -- reads ----------------------------------------------------------------

    def healthy_set(self) -> frozenset:
        with self._lock:
            return self._healthy

    def table(self) -> RoutingTable:
        return RoutingTable(self.replicas, self.healthy_set())

    def ages(self) -> dict[str, float | None]:
        """Seconds since each replica's last successful probe (None =
        never probed ok) — the staleness column the doctor reports."""
        now = time.perf_counter()
        with self._lock:
            return {n: (None if t is None else round(now - t, 3))
                    for n, t in self._last_ok.items()}

    # -- writes ---------------------------------------------------------------

    def mark_suspect(self, name: str) -> None:
        """Passive health: the forwarding path observed this replica dead
        (reconnect budget exhausted). Take it out of the healthy set NOW —
        the active prober re-admits it when it answers again."""
        with self._lock:
            if name not in self._fails:
                return
            self._fails[name] = self.fail_after
        self._obs_count("fleet/replica_suspect", replica=name)
        self._recompute()

    def probe_once(self) -> frozenset:
        """One synchronous probe round of every replica (what the poll
        thread runs on its interval; tests and the doctor call it directly
        so nothing sleeps). Returns the healthy set after the round."""
        from orp_tpu.serve.gateway import GatewayClient

        for r in self.replicas:
            ok = False
            draining = False
            try:
                with GatewayClient(r.addr, r.port,
                                   timeout_s=self.timeout_s) as c:
                    doc = c.health()
                ok = True
                draining = bool(doc.get("draining"))
            except (OSError, ValueError, RuntimeError):
                ok = False  # counted below; the health table IS the emission
            with self._lock:
                if ok and not draining:
                    self._fails[r.name] = 0
                    self._last_ok[r.name] = time.perf_counter()
                elif draining:
                    # its own gateway is already redirecting producers: out
                    # of the table immediately, no failure count needed
                    self._fails[r.name] = self.fail_after
                else:
                    self._fails[r.name] += 1
        self._recompute()
        return self.healthy_set()

    def _recompute(self) -> None:
        with self._lock:
            healthy = frozenset(n for n, f in self._fails.items()
                                if f < self.fail_after)
            changed = healthy != self._healthy
            self._healthy = healthy
        if changed:
            self._obs_count("fleet/health_change")
            self._flight("fleet_health", healthy=sorted(healthy))
            if self.on_change is not None:
                self.on_change(healthy)

    def _poll_loop(self) -> None:
        while not self._closed.wait(self.poll_s):
            try:
                self.probe_once()
            except Exception:  # orp: noqa[ORP009] -- emitted: the probe-crash counter below is the signal; the poller must outlive one bad round
                self._obs_count("fleet/probe_error")

    @staticmethod
    def _obs_count(name: str, n: int = 1, **labels) -> None:
        from orp_tpu.obs import count

        count(name, n, **labels)

    @staticmethod
    def _flight(kind: str, **fields) -> None:
        from orp_tpu.obs import flight

        flight.record(kind, **fields)

    def close(self) -> None:
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


class FleetHost:
    """The router a fleet gateway fronts: wears the ``ServeHost`` submit
    surface (``submit_block``/``stats``/``registry``/``close``) so the
    existing :class:`~orp_tpu.serve.gateway.ServeGateway` speaks the whole
    v2 delivery protocol to producers unchanged, while every admitted
    block is FORWARDED to its mapped replica.

    Forwarding lane: one :class:`~orp_tpu.serve.client.ResilientGateway
    Client` per replica with a FAST reconnect budget (``retry`` — default
    6 attempts, 20ms doubling to 250ms: a fleet re-routes around a dead
    replica in under a second instead of waiting out a 55s supervisor
    budget). A transient blip never surfaces: the client reconnects,
    RESUMEs its session and replays — exactly-once-serve holds one hop
    deeper. A real death exhausts the budget; the block's done-callback
    marks the replica suspect (:class:`ReplicaHealth` confirms on its next
    probe round) and re-routes the SAME block to the rendezvous successor
    (``max_reroutes`` bounds the walk; every hop excludes the replicas
    already observed dead). The producer-facing future resolves exactly
    once, so fleet-level ``duplicate_serves`` stays 0 by construction.
    """

    def __init__(self, replicas, *, registry=None, health=None,
                 retry=None, window: int = 32, timeout_s: float = 30.0,
                 max_reroutes: int = 3, health_poll_s: float = 1.0,
                 health_timeout_s: float = 2.0, health_fail_after: int = 2):
        from orp_tpu.guard.serve import GuardPolicy
        from orp_tpu.obs import state as obs_state
        from orp_tpu.obs.registry import Registry

        self.replicas = tuple(sorted(replicas, key=lambda r: r.name))
        if not self.replicas:
            raise FleetError("FleetHost needs at least one replica")
        st = obs_state()
        self.registry = (registry if registry is not None
                         else st.registry if st is not None else Registry())
        self._own_health = health is None
        self.health = health if health is not None else ReplicaHealth(
            self.replicas, poll_s=health_poll_s,
            timeout_s=health_timeout_s, fail_after=health_fail_after)
        self.retry = retry if retry is not None else GuardPolicy(
            max_retries=6, backoff_ms=20.0, backoff_cap_ms=250.0)
        self.window = int(window)
        self.timeout_s = float(timeout_s)
        self.max_reroutes = int(max_reroutes)
        self._lock = threading.Lock()
        self._clients: dict[str, object] = {}
        self._table: RoutingTable | None = None
        self._pending = {r.name: 0 for r in self.replicas}
        self._rows = {r.name: 0 for r in self.replicas}
        self._closed = False
        # per-replica scrape series interned ONCE here (handles kept — the
        # ORP015 discipline): the fleet gateway's /metrics answers routing
        # state before the first frame arrives
        self._healthy_gauge = {
            r.name: self.registry.gauge("fleet/replica_healthy",
                                        {"replica": r.name})
            for r in self.replicas
        }
        self._rows_counter = {
            r.name: self.registry.counter("fleet/forwarded_rows",
                                          {"replica": r.name})
            for r in self.replicas
        }

    # -- routing ---------------------------------------------------------------

    def table(self) -> RoutingTable:
        # called per forwarded block: rebuild the table (and touch the
        # gauges) only when the healthy set actually changed — the
        # rendezvous table is pure in (replicas, healthy)
        healthy = self.health.healthy_set()
        with self._lock:
            cached = self._table
        if cached is not None and cached.healthy == healthy:
            return cached
        t = RoutingTable(self.replicas, healthy)
        for name, g in self._healthy_gauge.items():
            g.set(1.0 if name in t.healthy else 0.0)
        with self._lock:
            self._table = t
        return t

    def route_sample(self, tenants=None) -> dict:
        """The routing view the HEALTH wire kind exports: version, healthy
        set, per-replica health age, and the mapping of a tenant sample —
        what ``orp doctor --fleet`` compares across gateways."""
        table = self.table()
        sample = list(tenants) if tenants else list(ROUTE_SAMPLE)
        try:
            mapping = table.mapping(sample)
        except NoHealthyReplica:
            mapping = {}
        return {
            "version": table.version(),
            "replicas": [r.name for r in table.replicas],
            "healthy": sorted(table.healthy),
            "ages_s": self.health.ages(),
            "map": mapping,
        }

    # -- forwarding ------------------------------------------------------------

    def _client(self, spec: ReplicaSpec):
        """The live forwarding client for ``spec`` — rebuilt when the
        previous one died (budget exhausted) or was closed. Construction
        connects (fast to a live replica, OSError to a dead one — the
        caller treats that exactly like a dead client)."""
        from orp_tpu.serve.client import ResilientGatewayClient

        with self._lock:
            c = self._clients.get(spec.name)
            if c is not None and not c.dead:
                return c
        # connect OUTSIDE the lock (the ORP012 discipline: a slow connect
        # must not head-of-line-block other replicas' forwards)
        fresh = ResilientGatewayClient(spec.addr, spec.port,
                                       window=self.window, retry=self.retry,
                                       timeout_s=self.timeout_s)
        with self._lock:
            closed = self._closed
            if not closed:  # raced close(): nothing may own this client now
                cur = self._clients.get(spec.name)
                if cur is None or cur.dead:
                    self._clients[spec.name] = fresh
                    return fresh
        if closed:
            fresh.close()
            raise FleetError("FleetHost is closed")
        # lost the build race to a concurrent forward: use the winner
        fresh.close()
        return cur

    def submit_block(self, tenant: str, date_idx: int, states, prices=None,
                     deadlines=None, *, trace=None):
        """Route one block to ``tenant``'s replica; returns a future
        resolving to its :class:`~orp_tpu.serve.ingest.BlockResult` —
        across replica blips (absorbed by reconnect-replay) and replica
        deaths (re-routed to the rendezvous successor)."""
        from orp_tpu.serve.batcher import SlimFuture

        with self._lock:
            if self._closed:
                raise RuntimeError("FleetHost is closed")
        outer = SlimFuture()
        self._forward(outer, tenant, int(date_idx), states, prices,
                      deadlines, trace, tried=())
        return outer

    def _forward(self, outer, tenant, date_idx, states, prices, deadlines,
                 trace, tried) -> None:
        from orp_tpu.obs import count as obs_count
        from orp_tpu.serve.gateway import GatewayError

        with self._lock:
            if self._closed:
                # the callback-driven re-route path can land here AFTER
                # close() — rebuilding a client now would leak its socket
                # and reader thread past shutdown
                outer.set_exception(FleetError(
                    "FleetHost closed while the block was re-routing — "
                    "it was NOT forwarded; resubmit on the new host"))
                return
        try:
            target = self.table().replica_for(tenant, exclude=tried)
        except NoHealthyReplica as e:
            outer.set_exception(e)
            return
        try:
            client = self._client(target)
            inner = client.submit_block_async(
                tenant, date_idx, states, prices, deadlines, trace=trace)
        except (OSError, RuntimeError, ValueError) as e:
            self._replica_failed(outer, tenant, date_idx, states, prices,
                                 deadlines, trace, tried, target, e)
            return
        with self._lock:
            self._pending[target.name] += 1
        n_rows = getattr(states, "shape", (1,))[0]

        def _done(f, name=target.name, client=client):
            with self._lock:
                self._pending[name] -= 1
            err = f.exception()
            if err is None:
                with self._lock:
                    self._rows[name] += n_rows
                self._rows_counter[name].inc(n_rows)
                outer.set_result(f.result())
                return
            dead = isinstance(err, OSError) or getattr(client, "dead", True)
            if isinstance(err, (GatewayError, OSError)) and dead:
                # the replica DIED under the frame (reconnect budget
                # exhausted / refused): re-route to the rendezvous
                # successor — the block is still in OUR hands, nothing
                # was lost, and the dead replica can never answer twice
                self._replica_failed(outer, tenant, date_idx, states,
                                     prices, deadlines, trace, tried,
                                     target, err)
                return
            # the replica ANSWERED (a structured ERROR frame — unknown
            # tenant, malformed block, a guard verdict): that is the
            # PRODUCER's error, not a health signal — re-routing it would
            # let one poison frame walk the whole fleet out of the
            # healthy set (found live: an unknown tenant marked every
            # replica suspect until NoHealthyReplica)
            outer.set_exception(err)

        inner.add_done_callback(_done)
        obs_count("fleet/forwarded", sink_event=False, replica=target.name)

    def _replica_failed(self, outer, tenant, date_idx, states, prices,
                        deadlines, trace, tried, target, err) -> None:
        from orp_tpu.obs import count as obs_count
        from orp_tpu.obs import flight

        obs_count("fleet/reroute", replica=target.name)
        flight.record("fleet_reroute", replica=target.name, tenant=tenant,
                      why=f"{type(err).__name__}: {err}"[:120])
        self.health.mark_suspect(target.name)
        tried = (*tried, target.name)
        if len(tried) > self.max_reroutes:
            outer.set_exception(FleetError(
                f"block for tenant {tenant!r} failed on {len(tried)} "
                f"replicas ({', '.join(tried)}): {err} — the fleet is "
                "down, not one replica"))
            return
        self._forward(outer, tenant, date_idx, states, prices, deadlines,
                      trace, tried)

    # -- the ServeHost-shaped introspection surface ---------------------------

    def stats(self) -> dict:
        """Per-replica forwarding state in the shape the gateway's health
        document expects (``live``/``pending``/``version`` per row)."""
        table = self.health.table()
        version = table.version()
        with self._lock:
            return {
                r.name: {
                    "live": r.name in table.healthy,
                    "pending": self._pending[r.name],
                    "version": version,
                    "rows": self._rows[r.name],
                    "address": f"{r.addr}:{r.port}",
                }
                for r in self.replicas
            }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()
        if self._own_health:
            self.health.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -- fleet dashboard aggregation ----------------------------------------------


def fleet_snapshot(per_gateway: dict) -> dict:
    """Merge per-gateway ``top_snapshot`` digests into one fleet view:
    summed rates and totals, the per-gateway table (p99/queue-age/shed),
    and routing agreement (``routing_consistent`` — every gateway's
    routing version identical). ``per_gateway``: ``{target: {"snap":
    top_snapshot(...), "routing": health_doc["routing"] | None}}``."""
    agg = {"requests": 0.0, "rows": 0.0, "gateway_rows": 0.0, "shed": 0.0,
           "busy": 0.0, "errors": 0.0}
    rates: dict[str, float] = {}
    gateways = {}
    versions = set()
    viewless = []
    for target, info in sorted(per_gateway.items()):
        snap = info["snap"]
        for k in agg:
            agg[k] += snap.get(k) or 0.0
        for k, v in (snap.get("rates") or {}).items():
            rates[k] = rates.get(k, 0.0) + v
        routing = info.get("routing") or {}
        if routing.get("version"):
            versions.add(routing["version"])
        else:
            # a gateway with NO routing view (a plain serving gateway
            # listed as a fleet gateway) is exactly the split-fleet
            # misconfiguration this line exists to expose — it must
            # never read as agreement
            viewless.append(target)
        gateways[target] = {
            "queue_age_p99_ms": snap.get("queue_age_p99_ms"),
            "gateway_rows": snap.get("gateway_rows"),
            "shed": snap.get("shed"),
            "busy": snap.get("busy"),
            "errors": snap.get("errors"),
            "rates": snap.get("rates") or {},
            "routing_version": routing.get("version"),
            "healthy": routing.get("healthy"),
        }
    return {
        **agg,
        "rates": rates,
        "gateways": gateways,
        "routing_versions": sorted(versions),
        "routing_viewless": viewless,
        "routing_consistent": len(versions) == 1 and not viewless,
    }


def render_fleet_top(snap: dict) -> str:
    """The ``orp top --fleet`` screen: fleet-wide rates + the per-gateway
    table + the routing-agreement line."""
    r = snap.get("rates", {})

    def rate(field):
        v = r.get(field + "_per_s")
        return "-" if v is None else f"{v:,.1f}/s"

    lines = [
        f"orp top — fleet ({len(snap.get('gateways') or {})} gateway(s))",
        f"req {rate('requests')}  gw-rows {rate('gateway_rows')}  "
        f"shed {rate('shed')}  busy {rate('busy')}  "
        f"errors {snap.get('errors', 0):,.0f}  routing "
        + ("CONSISTENT " + snap["routing_versions"][0]
           if snap.get("routing_consistent")
           else (f"NO VIEW from {snap.get('routing_viewless')}"
                 if snap.get("routing_viewless")
                 else f"SPLIT {snap.get('routing_versions')}")),
    ]
    gws = snap.get("gateways") or {}
    if gws:
        lines.append(f"{'gateway':<22}{'gw-rows':>12}{'shed':>8}{'busy':>8}"
                     f"{'errors':>8}{'queue p99 ms':>14}{'version':>14}")
        for target in sorted(gws):
            g = gws[target]

            def cell(v, fmt):
                return "-" if v is None else format(v, fmt)

            lines.append(
                f"{target:<22}"
                f"{cell(g.get('gateway_rows'), ',.0f'):>12}"
                f"{cell(g.get('shed'), ',.0f'):>8}"
                f"{cell(g.get('busy'), ',.0f'):>8}"
                f"{cell(g.get('errors'), ',.0f'):>8}"
                f"{cell(g.get('queue_age_p99_ms'), '.3f'):>14}"
                f"{(g.get('routing_version') or '-'):>14}")
    return "\n".join(lines)
