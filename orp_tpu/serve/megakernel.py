"""Mixed-date megakernel: one fused dispatch for a block spanning dates.

The bucketed engine keys every executable on ONE traced ``date_idx``, so a
block whose rows sit at different rebalance dates fragments into one
dispatch per distinct date — at the serve forward's measured ~1% roofline
fraction the device idles while Python pays that per-date dispatch tax.
This Pallas kernel runs the WHOLE mixed-date block in one program: the
grid walks the date axis, each step runs the full ~122-param MLP forward
for that date's parameters over the block and commits the rows whose
per-row date index matches.

Bitwise contract (the lowering-equivalence pin in tests/test_serve.py):
each grid step's layer matmul is the SAME 2-D ``dot`` (HIGHEST precision,
matching ``utils/precision.highest_matmul_precision`` on the bucketed
path) over the full block that the bucketed executable runs, and XLA row
results are batch-size-invariant, so selecting rows by date mask
reproduces the loop-of-buckets path exactly in f32. The masked-select
formulation is also why the kernel stays Mosaic-friendly: 2-D dots and
elementwise selects only — no gathers, no batched ``dot_general``.

Backend conditional exactly like ``qmc/pallas_mf.heston_qe_pallas``:
``interpret=None`` resolves to the Pallas interpreter off-TPU (the CPU
tier-1 suite exercises that path), compiled Mosaic on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from orp_tpu.serve.precision import dequantize_params, eval_model
from orp_tpu.train.backward import _split_holdings


def use_interpret(interpret: bool | None = None) -> bool:
    """Backend-conditional interpreter flag (the ``heston_qe_pallas``
    registry pattern): explicit wins, else interpret everywhere but TPU."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def _head_kernel(dates_ref, feats_ref, *refs, n_layers, slope):
    """One grid step = date ``d``: full MLP forward of the block under
    date ``d``'s parameters, rows committed where ``dates == d``. The
    output block is revisited by every step (sequential grid), so the
    running select accumulates the per-row gather without one."""
    out_ref = refs[-1]
    wrefs = refs[:-1]
    d = pl.program_id(0)
    x = feats_ref[...]
    for i in range(n_layers):
        w = wrefs[2 * i][0]       # (f_i, h_i) — this date's layer weights
        b = wrefs[2 * i + 1][0]   # (h_i,)
        x = jnp.dot(x, w, precision=jax.lax.Precision.HIGHEST) + b
        if i < n_layers - 1:
            x = jnp.where(x >= 0, x, slope * x)  # LeakyReLU (mlp.py)
    mask = dates_ref[...] == d    # (B, 1) broadcasts over the head width

    @pl.when(d == 0)
    def _init():
        out_ref[...] = jnp.where(mask, x, jnp.zeros_like(x))

    @pl.when(d != 0)
    def _select():
        out_ref[...] = jnp.where(mask, x, out_ref[...])


def _wmap(d):
    return (d, 0, 0)


def _bmap(d):
    return (d, 0)


def _rowmap(d):
    return (0, 0)


def mixed_head_forward(model, params_by_date, dates2d, feats, *,
                       interpret: bool):
    """Raw head outputs ``(B, n_outputs)`` of ``model`` where row ``r``
    uses ``params_by_date[..][dates2d[r, 0]]`` — the whole mixed-date
    block in ONE dispatch. ``feats`` must already be in ``model.dtype``;
    constraint head / value / dual-mode combines happen in the (jit)
    wrapper, not here."""
    n_layers = len(model.hidden) + 1
    n_dates = int(params_by_date["w0"].shape[0])
    rows = feats.shape[0]
    args, specs = [], []
    for i in range(n_layers):
        w = params_by_date[f"w{i}"]
        b = params_by_date[f"b{i}"]
        args += [w, b]
        specs += [pl.BlockSpec((1, *w.shape[1:]), _wmap),
                  pl.BlockSpec((1, *b.shape[1:]), _bmap)]
    kernel = functools.partial(_head_kernel, n_layers=n_layers,
                               slope=model.negative_slope)
    return pl.pallas_call(
        kernel,
        grid=(n_dates,),
        in_specs=[pl.BlockSpec((rows, 1), _rowmap),
                  pl.BlockSpec(feats.shape, _rowmap),
                  *specs],
        out_specs=pl.BlockSpec((rows, model.n_outputs), _rowmap),
        out_shape=jax.ShapeDtypeStruct((rows, model.n_outputs),
                                       feats.dtype),
        interpret=interpret,
    )(dates2d, feats, *args)


def _constrain(model, x):
    """``HedgeMLP.holdings``' head tail, applied to the kernel's raw
    outputs: identical ops, so bits match the bucketed path."""
    if model.constrain_self_financing:
        phi = x[..., 0]
        return jnp.stack([phi, 1.0 - phi], axis=-1)
    return x


@functools.partial(jax.jit, static_argnames=("model", "dual_mode",
                                             "holdings_combine",
                                             "precision", "interpret"))
def _eval_core_mixed(model, p1_all, p2_all, dates, feats, prices,
                     cost_of_capital, *, dual_mode, holdings_combine,
                     precision="f32", interpret=True):
    """The mixed-date twin of ``serve/engine._eval_core``: per-ROW date
    indices, one fused dispatch. Same tier semantics (int8 dequantizes to
    f32 before the forward, bf16 runs the tier-replaced model and casts
    outputs back to f32); same dual-mode combines as the serve-side
    ``_date_outputs_core`` call (``prices_t1 = 0`` ⇒ the var-residual leg
    vanishes, so only value + holdings survive)."""
    if precision == "int8":
        p1_all = dequantize_params(p1_all)
        p2_all = dequantize_params(p2_all)
    m = eval_model(model, precision)
    feats = feats.astype(m.dtype)
    d2 = dates[:, None]
    raw1 = mixed_head_forward(m, p1_all, d2, feats, interpret=interpret)
    h1 = _constrain(m, raw1)
    p = prices.astype(m.dtype)
    if dual_mode == "mse_only":
        comb = h1
        v = jnp.sum(h1 * p, axis=-1)
    else:
        raw2 = mixed_head_forward(m, p2_all, d2, feats,
                                  interpret=interpret)
        h2 = _constrain(m, raw2)
        g = jnp.sum(h1 * p, axis=-1)   # value under params1 (g_pre/g_t)
        h = jnp.sum(h2 * p, axis=-1)   # value under params2
        v = g + cost_of_capital * (h - g)
        if dual_mode == "shared":
            # serve-side shared semantics (engine._eval_core): g_pre is
            # the stored params1 value, ledger holdings read params2
            comb = h2
        elif holdings_combine == "py":
            comb = h1 + cost_of_capital * (h1 - h2)  # RP.py:114 sign quirk
        else:
            comb = h1 + cost_of_capital * (h2 - h1)  # Single#18
    phi, psi = _split_holdings(comb)
    if precision == "bf16":
        phi = phi.astype(jnp.float32)
        psi = psi.astype(jnp.float32)
        v = v.astype(jnp.float32)
    return phi, psi, v


def loop_of_buckets(engine, dates, states, prices=None):
    """The fragmentation baseline the megakernel replaces: one bucketed
    engine dispatch per DISTINCT date, rows scattered back. The bench's
    "megakernel off" arm and the bitwise-equivalence test's reference."""
    dates = np.asarray(dates, np.int64).reshape(-1)
    states = np.asarray(states)
    n = states.shape[0]
    phi = psi = v = None
    for d in np.unique(dates):
        m = dates == d
        p_, s_, v_ = engine.evaluate(
            int(d), states[m], None if prices is None else prices[m])
        if phi is None:
            phi = np.zeros((n, *p_.shape[1:]), p_.dtype)
            psi = np.zeros((n, *s_.shape[1:]), s_.dtype)
            v = (np.zeros((n, *v_.shape[1:]), v_.dtype)
                 if v_ is not None else None)
        phi[m] = p_
        psi[m] = s_
        if v is not None:
            v[m] = v_
    return phi, psi, v
