"""Achieved-FLOP/s and MFU accounting for the hedge workload (VERDICT r4
item 5: "no achieved-FLOP/s or MFU accounting anywhere").

The analytic model counts the algorithm's USEFUL arithmetic — the number a
user would compute from the math, not XLA's instruction census — so MFU
here answers "what fraction of the chip's matmul ceiling does the
*algorithm* extract", the standard convention. The dominant GN term is the
blocked Gram pair ``JᵀWJ`` / ``Jᵀr`` (2nP² + 2nP per iteration, P = 106
for the 1-feature hedge MLP — the Phi_Psi head is always 2-wide; the
self-financing constraint is applied downstream of it); everything else (per-sample grads ~3x a
forward pass, the P×P solve, the line-search loss) is sub-percent at
benchmark shapes. Validated against XLA's own ``cost_analysis`` in
``tests/test_flops.py``.

Peaks: v5e lists 197 TFLOP/s bf16 per chip. The framework's matmuls are
pinned to f32 (``utils/precision.py`` — the §6b bf16-Gram defect), which
XLA implements as a multi-pass bf16 decomposition, ~6x the work, so the
realistic ceiling for THIS workload is ~33 TFLOP/s; both denominators are
reported. Why the numbers are small either way: the workload is
latency/bandwidth-bound, not FLOP-bound — 52 sequential dates of 106-wide
Grams leave the 128x128 MXU mostly idle (SCALING.md §3 MFU note).
"""

from __future__ import annotations

PEAK_BF16_V5E = 197e12  # published v5e per-chip bf16 peak, FLOP/s
F32_MATMUL_PASSES = 6   # f32 matmul on the MXU ~ 6-pass bf16 decomposition

# GBM log-Euler per path-step: ndtri polynomial (~25) + mul/add chain (~5).
# Sobol itself is uint32 bit arithmetic — integer ops, not FLOPs.
SIM_FLOPS_PER_PATH_STEP = 30


def mlp_param_count(n_features: int, hidden=(8, 8), n_outputs: int = 2) -> int:
    """Parameter count of models.mlp.HedgeMLP (dense chain + biases):
    106 for the 1-feature European config (2-wide Phi_Psi head)."""
    sizes = (n_features, *hidden, n_outputs)
    return sum(a * b + b for a, b in zip(sizes[:-1], sizes[1:]))


def mlp_forward_flops(n_features: int, hidden=(8, 8), n_outputs: int = 2) -> int:
    """Multiply-adds of one forward pass, counted as 2 FLOPs each."""
    sizes = (n_features, *hidden, n_outputs)
    return sum(2 * a * b for a, b in zip(sizes[:-1], sizes[1:]))


def gn_iteration_flops(n_rows: int, p: int, fwd: int) -> int:
    """One LM-GN iteration at ``n_rows`` samples, ``p`` parameters:
    Gram pair (2nP² + 2nP) + per-sample grads (~3 fwd) + residual fwd +
    line-search loss fwd + the P×P solve."""
    gram = 2 * n_rows * p * p + 2 * n_rows * p
    net = n_rows * (3 * fwd + 2 * fwd)          # J rows + resid + cand loss
    solve = (2 * p ** 3) // 3
    return gram + net + solve


def gn_walk_flops(n_paths: int, n_dates: int, iters_first: int,
                  iters_warm: int, n_features: int = 1,
                  n_outputs: int = 2) -> int:
    """Total useful FLOPs of the fused GN backward walk (the north-star
    benchmark): one ``iters_first`` fit + (n_dates-1) ``iters_warm`` fits,
    every fit full-batch over all paths."""
    p = mlp_param_count(n_features, n_outputs=n_outputs)
    fwd = mlp_forward_flops(n_features, n_outputs=n_outputs)
    iters = iters_first + (n_dates - 1) * iters_warm
    return iters * gn_iteration_flops(n_paths, p, fwd)


def adam_walk_flops(n_paths: int, n_dates: int, epochs_first: int,
                    epochs_warm: int, n_features: int = 1,
                    n_outputs: int = 2) -> int:
    """Adam walk: fwd+bwd (~3 fwd) per sample per epoch, full dataset."""
    fwd = mlp_forward_flops(n_features, n_outputs=n_outputs)
    epochs = epochs_first + (n_dates - 1) * epochs_warm
    return epochs * n_paths * 3 * fwd


def sim_flops(n_paths: int, n_steps: int,
              per_step: int = SIM_FLOPS_PER_PATH_STEP) -> int:
    return n_paths * n_steps * per_step


def mfu(flops: float, wall_s: float, peak: float = PEAK_BF16_V5E) -> float:
    """Model FLOP utilization: achieved useful FLOP/s over the peak."""
    return flops / wall_s / peak


def phase_report(flops: float, wall_s: float) -> dict:
    """The fields the profile stage emits per phase: achieved FLOP/s plus
    MFU against both the bf16 peak and the f32-matmul ceiling."""
    fps = flops / wall_s
    return {
        "flops": int(flops),
        "flops_per_s": round(fps, 1),
        "mfu_bf16_peak": round(fps / PEAK_BF16_V5E, 5),
        "mfu_f32_ceiling": round(fps * F32_MATMUL_PASSES / PEAK_BF16_V5E, 5),
    }
