"""Atomic small-file writes: write-temp-then-rename, same directory.

Every side file the persistence layers write next to their artifacts —
``run_fingerprint.txt``, ``bundle.json``, ``aot/aot.json``, the per-bucket
executable blobs, per-date checkpoint digests — is a compatibility or
integrity GUARD. A guard half-written by a killed process is worse than a
missing one: it can pass a naive existence check while carrying garbage.
``os.replace`` of a same-directory temp file is atomic on POSIX and
Windows, so readers only ever observe the old content or the complete new
content, never a torn write.

(The orbax checkpoint payloads themselves already commit atomically via
the CheckpointManager's finalisation protocol; this module covers the
plain-text/bytes side files written around them.)
"""

from __future__ import annotations

import os
import pathlib
import tempfile


def _atomic_write(path: str | pathlib.Path, data, *, binary: bool) -> None:
    p = pathlib.Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=p.parent, prefix=f".{p.name}.", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb" if binary else "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
    except BaseException:
        # never leave the temp behind a failed write (ENOSPC, kill mid-
        # fsync): the artifact dir must hold guards and payloads only
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str | pathlib.Path, text: str) -> None:
    """Write ``text`` to ``path`` atomically (temp file + ``os.replace``)."""
    _atomic_write(path, text, binary=False)


def atomic_write_bytes(path: str | pathlib.Path, blob: bytes) -> None:
    """Write ``blob`` to ``path`` atomically (temp file + ``os.replace``)."""
    _atomic_write(path, blob, binary=True)
