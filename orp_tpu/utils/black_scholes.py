"""Closed-form Black-Scholes oracle (shared by tests and benchmarks).

The reference has no analytic pricer — its notebooks eyeball the learned V0
against the discounted mean payoff (``Euro#20``). SURVEY.md §6 computes
BS ~ 10.39 for the Euro config as the independent oracle; this module is that
oracle, defined once.
"""

from __future__ import annotations

from math import erf, exp, log, sqrt


def _N(x: float) -> float:
    return 0.5 * (1.0 + erf(x / sqrt(2.0)))


def bs_call(s0: float, k: float, r: float, sigma: float, T: float) -> tuple[float, float]:
    """European call (price, delta)."""
    d1 = (log(s0 / k) + (r + sigma * sigma / 2.0) * T) / (sigma * sqrt(T))
    d2 = d1 - sigma * sqrt(T)
    return s0 * _N(d1) - k * exp(-r * T) * _N(d2), _N(d1)


def bs_put(s0: float, k: float, r: float, sigma: float, T: float) -> tuple[float, float]:
    """European put (price, delta) via parity."""
    call, delta_c = bs_call(s0, k, r, sigma, T)
    return call - s0 + k * exp(-r * T), delta_c - 1.0
