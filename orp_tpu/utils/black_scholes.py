"""Closed-form Black-Scholes oracle (shared by tests and benchmarks).

The reference has no analytic pricer — its notebooks eyeball the learned V0
against the discounted mean payoff (``Euro#20``). SURVEY.md §6 computes
BS ~ 10.39 for the Euro config as the independent oracle; this module is that
oracle, defined once.
"""

from __future__ import annotations

from math import erf, exp, log, sqrt


def _N(x: float) -> float:
    return 0.5 * (1.0 + erf(x / sqrt(2.0)))


def bs_call(s0: float, k: float, r: float, sigma: float, T: float) -> tuple[float, float]:
    """European call (price, delta)."""
    g = bs_greeks(s0, k, r, sigma, T, kind="call")
    return g["price"], g["delta"]


def bs_put(s0: float, k: float, r: float, sigma: float, T: float) -> tuple[float, float]:
    """European put (price, delta) via parity."""
    call, delta_c = bs_call(s0, k, r, sigma, T)
    return call - s0 + k * exp(-r * T), delta_c - 1.0


def _phi(x: float) -> float:
    return exp(-0.5 * x * x) / sqrt(2.0 * 3.141592653589793)


def bs_greeks(
    s0: float, k: float, r: float, sigma: float, T: float, kind: str = "call"
) -> dict[str, float]:
    """Full closed-form greeks — the oracle for ``risk/greeks.py``'s pathwise
    AD estimators. Theta is calendar decay dV/dt (negative for long calls)."""
    d1 = (log(s0 / k) + (r + sigma * sigma / 2.0) * T) / (sigma * sqrt(T))
    d2 = d1 - sigma * sqrt(T)
    disc = exp(-r * T)
    gamma = _phi(d1) / (s0 * sigma * sqrt(T))
    vega = s0 * _phi(d1) * sqrt(T)
    if kind == "call":
        price, delta = s0 * _N(d1) - k * disc * _N(d2), _N(d1)
        theta = -s0 * _phi(d1) * sigma / (2.0 * sqrt(T)) - r * k * disc * _N(d2)
        rho = k * T * disc * _N(d2)
    elif kind == "put":
        price, delta = k * disc * _N(-d2) - s0 * _N(-d1), _N(d1) - 1.0
        theta = -s0 * _phi(d1) * sigma / (2.0 * sqrt(T)) + r * k * disc * _N(-d2)
        rho = -k * T * disc * _N(-d2)
    else:
        raise ValueError(f"kind must be 'call' or 'put', got {kind!r}")
    return {
        "price": price, "delta": delta, "gamma": gamma, "vega": vega,
        "rho": rho, "theta": theta,
    }
