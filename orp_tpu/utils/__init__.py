"""Cross-cutting utilities: checkpointing, fingerprints, profiling/timing."""

from orp_tpu.utils.black_scholes import bs_call, bs_greeks, bs_put
from orp_tpu.utils.checkpoint import latest_step, load_checkpoint, save_checkpoint
from orp_tpu.utils.crr import crr_price
from orp_tpu.utils.fingerprint import (
    check_fingerprint,
    policy_fingerprint,
    read_fingerprint,
    verify_fingerprint,
    verify_policy_compat,
    write_fingerprint,
)
from orp_tpu.utils.profiling import timed, trace

__all__ = [
    "bs_call",
    "bs_greeks",
    "bs_put",
    "check_fingerprint",
    "crr_price",
    "latest_step",
    "load_checkpoint",
    "policy_fingerprint",
    "read_fingerprint",
    "save_checkpoint",
    "timed",
    "trace",
    "verify_fingerprint",
    "verify_policy_compat",
    "write_fingerprint",
]
