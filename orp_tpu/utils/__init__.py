"""Cross-cutting utilities: checkpointing, profiling/timing."""

from orp_tpu.utils.black_scholes import bs_call, bs_greeks, bs_put
from orp_tpu.utils.checkpoint import latest_step, load_checkpoint, save_checkpoint
from orp_tpu.utils.crr import crr_price
from orp_tpu.utils.profiling import timed, trace

__all__ = [
    "bs_call",
    "bs_greeks",
    "bs_put",
    "crr_price",
    "latest_step",
    "load_checkpoint",
    "save_checkpoint",
    "timed",
    "trace",
]
