"""Approximate analytic oracle for arithmetic basket calls.

Moment-matched lognormal ("Levy") approximation: the arithmetic basket
``B_T = sum_i w_i S_i(T)`` of correlated GBMs has no closed-form law, but its
first two moments do. Matching them to a lognormal gives a Black-formula price
that is exact in both degenerate limits —

- A = 1: the basket IS a single GBM -> Black-Scholes exactly;
- rho = 1 with equal sigmas: all assets are comonotone copies -> the basket is
  a single lognormal on the basket spot -> Black-Scholes exactly —

which makes those limits *executable oracles* for the implementation (see
``tests/test_basket.py``), while at moderate correlations the approximation is
good to ~10bp for typical equity-basket parameters (the QMC estimator in
``benchmarks/baseline_configs.py`` config 5 is compared against it).

Reference anchor: the reference has no basket machinery at all — this oracle
backs BASELINE.json config 5 (5-asset basket-call hedge), the TPU build's
multi-asset extension of ``European Options.ipynb``.
"""

from __future__ import annotations

import numpy as np

from orp_tpu.utils.black_scholes import _N


def basket_call_mm(
    s0, weights, strike: float, r: float, sigmas, corr, T: float
) -> tuple[float, float]:
    """Moment-matched lognormal price of a European arithmetic basket call.

    Returns ``(price, effective_vol)`` where ``effective_vol`` is the matched
    lognormal's annualised vol ``sqrt(ln(m2/m1^2)/T)``.
    """
    s0 = np.asarray(s0, np.float64)
    w = np.asarray(weights, np.float64)
    sig = np.asarray(sigmas, np.float64)
    rho = np.asarray(corr, np.float64)

    fwd = w * s0 * np.exp(r * T)                     # per-asset forwards
    m1 = fwd.sum()
    # E[B^2] = sum_ij w_i w_j S_i0 S_j0 exp(2rT + rho_ij sig_i sig_j T)
    cov = rho * np.outer(sig, sig) * T
    m2 = float(np.outer(fwd, fwd).ravel() @ np.exp(cov).ravel())

    v2 = np.log(m2 / (m1 * m1))                      # matched total variance
    if v2 <= 0:  # numerically degenerate (zero vol)
        return float(np.exp(-r * T) * max(m1 - strike, 0.0)), 0.0
    v = np.sqrt(v2)
    d1 = (np.log(m1 / strike) + 0.5 * v2) / v
    d2 = d1 - v
    price = float(np.exp(-r * T) * (m1 * _N(float(d1)) - strike * _N(float(d2))))
    return price, float(v / np.sqrt(T))
