"""Numerical sanitizers (SURVEY.md §5 "race detection / sanitizers").

The reference needs no thread sanitizers (single-threaded NumPy); the JAX
equivalent of a sanitizer pass is NaN/Inf detection on jitted programs plus
``checkify`` for in-kernel assertions.
"""

from __future__ import annotations

import contextlib

import jax
from jax.experimental import checkify


@contextlib.contextmanager
def nan_debug():
    """Enable ``jax_debug_nans`` within the block: any NaN produced by a jitted
    computation raises immediately with the offending primitive located."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def checked(fn, *, errors=checkify.float_checks):
    """Wrap ``fn`` with checkify float checks: returns ``checked_fn`` whose
    first output is an error carrier — call ``err.throw()`` to surface NaN/Inf
    divisions etc. raised inside jit/scan, where Python exceptions can't."""
    return checkify.checkify(fn, errors=errors)
