"""Semi-analytic Heston oracle (shared by tests and benchmarks).

Companion to ``utils/black_scholes.py``: the reference has no SV pricer at all
— its vol-CIR pension runs are eyeballed against discounted mean payoffs
(``Multi Time Step.ipynb#32``). The framework's corrected Heston kernel
(``sde/kernels.py`` ``simulate_heston_log``) needs a closed-form target the
way the GBM kernels have Black-Scholes, so the Heston hedge (BASELINE.json
config 4) can be pinned to basis points instead of a ballpark.

Implementation: Heston (1993) characteristic function in the Albrecher et al.
"little Heston trap" form (continuous in the principal branch of the complex
log, so no branch-cut unwrapping is needed), Gil-Pelaez inversion for the two
in-the-money probabilities, fixed Gauss-Legendre quadrature on ``u in
(0, u_max]``. Host-side NumPy — this is an oracle, not a device kernel.
"""

from __future__ import annotations

from math import exp, log, sqrt

import numpy as np


def _heston_cf(
    u: np.ndarray,
    T: float,
    s0: float,
    r: float,
    v0: float,
    kappa: float,
    theta: float,
    xi: float,
    rho: float,
) -> np.ndarray:
    """Characteristic function E[exp(i u ln S_T)] ("little trap" form)."""
    iu = 1j * u
    beta = kappa - rho * xi * iu
    d = np.sqrt(beta * beta + xi * xi * (iu + u * u))
    g = (beta - d) / (beta + d)
    edt = np.exp(-d * T)
    C = r * iu * T + (kappa * theta / (xi * xi)) * (
        (beta - d) * T - 2.0 * np.log((1.0 - g * edt) / (1.0 - g))
    )
    D = ((beta - d) / (xi * xi)) * ((1.0 - edt) / (1.0 - g * edt))
    return np.exp(C + D * v0 + iu * log(s0))


def heston_call(
    s0: float,
    k: float,
    r: float,
    T: float,
    *,
    v0: float,
    kappa: float,
    theta: float,
    xi: float,
    rho: float,
    u_max: float = 200.0,
    n_quad: int = 2048,
) -> float:
    """European call under Heston: ``S0 P1 - K e^{-rT} P2`` via Gil-Pelaez.

    Defaults resolve the ATM 1y config of ``HestonConfig`` to well below 0.1 bp
    (checked in ``tests/test_heston_oracle.py`` against quadrature refinement,
    the xi->0 Black-Scholes limit, and put-call parity).
    """
    x, w = np.polynomial.legendre.leggauss(n_quad)
    u = 0.5 * u_max * (x + 1.0)  # map [-1,1] -> (0, u_max]
    w = 0.5 * u_max * w
    lnk = log(k)

    cf = _heston_cf(u, T, s0, r, v0, kappa, theta, xi, rho)
    cf_shift = _heston_cf(u - 1j, T, s0, r, v0, kappa, theta, xi, rho)
    # E[S_T] = cf(-i) = S0 e^{rT} exactly; use the closed form for stability
    phase = np.exp(-1j * u * lnk) / (1j * u)
    p2 = 0.5 + np.sum(w * np.real(phase * cf)) / np.pi
    p1 = 0.5 + np.sum(w * np.real(phase * cf_shift)) / (np.pi * s0 * exp(r * T))
    return s0 * p1 - k * exp(-r * T) * p2


def heston_put(s0: float, k: float, r: float, T: float, **kw) -> float:
    """European put via put-call parity."""
    return heston_call(s0, k, r, T, **kw) - s0 + k * exp(-r * T)
