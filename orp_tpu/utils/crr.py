"""Cox-Ross-Rubinstein binomial oracle for Bermudan/American options.

Host-side NumPy f64 (an oracle, not a compute path — same policy as
``utils/black_scholes.py``/``utils/heston.py``). The reference has no early
exercise at all; this pins the framework's LSM pricer (``train/lsm.py``).
"""

from __future__ import annotations

import math

import numpy as np


def crr_price(
    s0: float,
    k: float,
    r: float,
    sigma: float,
    T: float,
    *,
    kind: str = "put",
    exercise: str = "american",
    n_steps: int = 2000,
    exercise_every: int | None = None,
) -> float:
    """Binomial price. ``exercise``: "european" | "american" | "bermudan"
    (Bermudan exercises only every ``exercise_every`` tree steps, so choose
    ``n_steps`` divisible by the number of exercise dates)."""
    if kind not in ("call", "put"):
        raise ValueError(f"kind must be 'call' or 'put', got {kind!r}")
    if exercise not in ("european", "american", "bermudan"):
        raise ValueError(f"unknown exercise style {exercise!r}")
    if exercise == "bermudan":
        if not exercise_every or n_steps % exercise_every:
            raise ValueError(
                "bermudan needs exercise_every dividing n_steps "
                f"(got {exercise_every}, {n_steps})"
            )
    dt = T / n_steps
    u = math.exp(sigma * math.sqrt(dt))
    d = 1.0 / u
    disc = math.exp(-r * dt)
    p = (math.exp(r * dt) - d) / (u - d)
    if not 0.0 < p < 1.0:
        raise ValueError("CRR no-arbitrage violated: refine n_steps")

    j = np.arange(n_steps + 1)
    s_t = s0 * u ** (n_steps - j) * d ** j
    sign = 1.0 if kind == "call" else -1.0
    v = np.maximum(sign * (s_t - k), 0.0)
    for step in range(n_steps - 1, -1, -1):
        v = disc * (p * v[:-1] + (1.0 - p) * v[1:])
        can_exercise = exercise == "american" or (
            exercise == "bermudan" and step > 0 and step % exercise_every == 0
        )
        if can_exercise:
            s_t = s0 * u ** (step - np.arange(step + 1)) * d ** np.arange(step + 1)
            v = np.maximum(v, np.maximum(sign * (s_t - k), 0.0))
    return float(v[0])
