"""Tracing / timing helpers (SURVEY.md §5 "tracing/profiling").

The reference times hot loops with ``perf_counter`` prints
(``Single Time Step.ipynb#7`` etc.). Here:

- ``trace(name)`` — ``jax.profiler.TraceAnnotation`` context manager, so
  framework phases (simulate / fit / analytics) show up as named spans in a
  TensorBoard/XProf capture;
- ``timed(fn, *args)`` — jit-aware wall timing: blocks on the result tree, so
  the figure is real device time, not dispatch time.
"""

from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def trace(name: str):
    with jax.profiler.TraceAnnotation(name):
        yield


def timed(fn, *args, **kwargs):
    """Run ``fn`` and return ``(result, seconds)``, blocking until device-done."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0
