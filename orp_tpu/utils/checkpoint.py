"""Step-indexed pytree checkpointing for preemptible backward-induction runs.

The reference has no disk checkpointing (SURVEY.md §5): its only recovery
mechanisms are Keras best-weight restoration inside one ``fit`` and the warm
start across dates. This module adds the missing piece for long TPU jobs —
persist ``(params, this date's ledger columns)`` after each backward step so a
preempted run resumes at the next date instead of re-simulating/retraining.

Built on ``orbax.checkpoint.CheckpointManager`` (the supported step-management
API: atomic finalisation, latest-step discovery, retention). A *fingerprint*
side-file guards resume compatibility: a directory written by a different run
configuration refuses to resume instead of silently returning stale results.
"""

from __future__ import annotations

import pathlib

import jax
import orbax.checkpoint as ocp

_FPRINT = "run_fingerprint.txt"


def _manager(directory: str | pathlib.Path) -> ocp.CheckpointManager:
    # every step is retained: saves are per-date *increments* (one ledger
    # column each), so resume replays all of them — total disk is the ledger
    # size itself, and cumulative write I/O stays O(n_dates * paths) instead of
    # the O(n_dates^2 * paths) that re-saving accumulated state would cost
    return ocp.CheckpointManager(
        pathlib.Path(directory).absolute(),
        options=ocp.CheckpointManagerOptions(max_to_keep=None),
    )


def check_fingerprint(directory: str | pathlib.Path, fingerprint: str) -> None:
    """Write the run fingerprint on first use; refuse a mismatched directory."""
    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    f = d / _FPRINT
    if f.exists():
        saved = f.read_text()
        if saved != fingerprint:
            raise ValueError(
                f"checkpoint dir {d} belongs to a different run config:\n"
                f"  saved:   {saved}\n  current: {fingerprint}\n"
                "use a fresh --checkpoint-dir (or delete the old one)"
            )
    else:
        f.write_text(fingerprint)


def save_checkpoint(directory: str | pathlib.Path, step: int, state) -> None:
    """Persist ``state`` (any pytree of arrays/scalars) under ``step``."""
    with _manager(directory) as mgr:
        mgr.save(
            step,
            args=ocp.args.PyTreeSave(jax.tree.map(jax.numpy.asarray, state)),
            force=True,
        )
        mgr.wait_until_finished()


def latest_step(directory: str | pathlib.Path) -> int | None:
    """Highest saved step in ``directory``, or None if nothing is saved."""
    if not pathlib.Path(directory).is_dir():
        return None
    with _manager(directory) as mgr:
        return mgr.latest_step()


def load_checkpoint(directory: str | pathlib.Path, step: int):
    """Restore the pytree saved at ``step``."""
    with _manager(directory) as mgr:
        return mgr.restore(step)


def load_checkpoints(directory: str | pathlib.Path, steps):
    """Yield the pytrees saved at each of ``steps`` from ONE open manager.

    Resume replays every per-date increment; constructing a manager per step
    would re-enumerate the whole directory each time (quadratic in walk length
    now that all steps are retained).
    """
    with _manager(directory) as mgr:
        for step in steps:
            yield mgr.restore(step)
