"""Step-indexed pytree checkpointing for preemptible backward-induction runs.

The reference has no disk checkpointing (SURVEY.md §5): its only recovery
mechanisms are Keras best-weight restoration inside one ``fit`` and the warm
start across dates. This module adds the missing piece for long TPU jobs —
persist ``(params, this date's ledger columns)`` after each backward step so a
preempted run resumes at the next date instead of re-simulating/retraining.

Built on ``orbax.checkpoint.CheckpointManager`` (the supported step-management
API: atomic finalisation, latest-step discovery, retention). A *fingerprint*
side file guards resume compatibility: a directory written by a different run
configuration refuses to resume instead of silently returning stale results.
The fingerprint mechanics live in ``orp_tpu/utils/fingerprint.py``, shared
with the hedge-policy bundles of ``orp_tpu/serve``.

Integrity (orp_tpu.guard): every save also records a SHA-256 digest of the
step's leaves in an atomically-written side file
(``orp_digest_<step>.sha256``); every restore recomputes and compares. A
truncated or bit-rotted step — the on-disk state a process death or a bad
copy leaves behind — is DETECTED AND REFUSED with a clean ``ValueError``
instead of resuming a walk from garbage (orbax's own commit protocol makes
torn *writes* unlikely; the digest also catches post-commit damage, which
no commit protocol can).
"""

from __future__ import annotations

import hashlib
import pathlib
import warnings

import jax
import orbax.checkpoint as ocp

from orp_tpu.utils.atomic import atomic_write_text
from orp_tpu.utils.fingerprint import check_fingerprint

__all__ = [
    "check_fingerprint",
    "save_checkpoint",
    "latest_step",
    "latest_complete_step",
    "load_checkpoint",
    "load_checkpoints",
    "state_digest",
]

_DIGEST_FILE = "orp_digest_{step}.sha256"


def state_digest(state) -> str:
    """SHA-256 over every leaf's key path, dtype, shape and raw bytes —
    the integrity identity of one checkpoint step. Computed on the exact
    (``jnp.asarray``-normalised) tree handed to orbax at save time and on
    the restored tree at load time; any torn/flipped byte in between
    changes it."""
    import numpy as np

    h = hashlib.sha256()
    for path, leaf in jax.tree_util.tree_leaves_with_path(state):
        x = np.asarray(leaf)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(x.dtype).encode())
        h.update(str(x.shape).encode())
        h.update(np.ascontiguousarray(x).tobytes())
    return h.hexdigest()


def _manager(directory: str | pathlib.Path) -> ocp.CheckpointManager:
    # every step is retained: saves are per-date *increments* (one ledger
    # column each), so resume replays all of them — total disk is the ledger
    # size itself, and cumulative write I/O stays O(n_dates * paths) instead of
    # the O(n_dates^2 * paths) that re-saving accumulated state would cost
    return ocp.CheckpointManager(
        pathlib.Path(directory).absolute(),
        options=ocp.CheckpointManagerOptions(max_to_keep=None),
    )


def save_checkpoint(directory: str | pathlib.Path, step: int, state) -> None:
    """Persist ``state`` (any pytree of arrays/scalars) under ``step``,
    plus its integrity digest side file (written atomically AFTER orbax
    finalises the step: a digest must never exist for a payload that
    didn't fully commit).

    Leaves are normalised to HOST numpy first, so the on-disk layout is
    TOPOLOGY-FREE: a step saved from an 8-device path-sharded walk restores
    identically on one device (orbax would otherwise persist the sharding
    and warn — correctly — that restoring on a different topology is
    unsafe). This is what lets a preempted pod slice ``--resume`` on
    whatever hardware survives (pinned bitwise for adam in
    ``tests/test_guard.py::test_resume_across_topology``); the gather costs
    nothing new — the integrity digest below already reads every leaf's
    host bytes."""
    import numpy as np

    state = jax.tree.map(np.asarray, state)
    with _manager(directory) as mgr:
        if step in mgr.all_steps():
            # redoing an existing step (e.g. a torn save whose digest never
            # landed, being recomputed on resume): this orbax refuses to
            # re-save a committed step even under force, so clear it first
            mgr.delete(step)
        mgr.save(step, args=ocp.args.PyTreeSave(state), force=True)
        mgr.wait_until_finished()
    atomic_write_text(
        pathlib.Path(directory) / _DIGEST_FILE.format(step=step),
        state_digest(state),
    )


def latest_step(directory: str | pathlib.Path) -> int | None:
    """Highest saved step in ``directory``, or None if nothing is saved."""
    if not pathlib.Path(directory).is_dir():
        return None
    with _manager(directory) as mgr:
        return mgr.latest_step()


def latest_complete_step(directory: str | pathlib.Path) -> int | None:
    """Highest step that BOTH committed in orbax and carries its integrity
    digest — the step resume may trust.

    A kill can land between orbax's commit and the digest write; that
    leaves a payload-complete but UNVERIFIABLE latest step. Refusing the
    whole directory for it would brick exactly the recovery the
    checkpoint layer exists for, so resume treats that one step as
    not-saved (its date is recomputed) and continues from the step below.
    Only the latest step can legitimately lack a digest — each earlier
    save finished its digest before the next began — so a digest-less
    MIDDLE step still refuses in the loaders (partial copy / pre-guard
    layout).
    """
    last = latest_step(directory)
    if last is None:
        return None
    if (pathlib.Path(directory) / _DIGEST_FILE.format(step=last)).exists():
        return last
    warnings.warn(
        f"checkpoint step {last} in {pathlib.Path(directory)} committed "
        "without its integrity digest (save was interrupted between commit "
        "and digest write); treating it as unsaved — that step will be "
        "recomputed on resume",
        stacklevel=2,
    )
    return last - 1 if last > 0 else None


def _verified(directory: str | pathlib.Path, step: int, restored):
    """Digest-check one restored step; returns it or refuses loudly."""
    df = pathlib.Path(directory) / _DIGEST_FILE.format(step=step)
    if not df.exists():
        raise ValueError(
            f"checkpoint step {step} in {pathlib.Path(directory)} has no "
            f"integrity digest ({df.name}) — a pre-guard layout, a partial "
            "copy, or a save torn between commit and digest write; refusing "
            "to resume from unverifiable state (resume callers should pick "
            "their step via latest_complete_step)"
        )
    want = df.read_text().strip()
    got = state_digest(restored)
    if got != want:
        raise ValueError(
            f"checkpoint step {step} in {pathlib.Path(directory)} failed its "
            f"integrity check (digest {got[:12]}… != recorded {want[:12]}…) — "
            "truncated or corrupted on disk; refusing to resume"
        )
    return restored


def _restore(mgr: ocp.CheckpointManager, directory, step: int):
    # explicit PyTreeRestore: a fresh manager (new process — exactly the
    # resume case) cannot infer the handler from the directory alone and
    # raises KeyError 'Item "default" ... could not be restored'
    try:
        restored = mgr.restore(step, args=ocp.args.PyTreeRestore())
    except Exception as e:
        # orbax surfaces a truncated/half-deleted step as whatever its
        # storage layer happened to hit (KeyError, OSError, msgpack
        # errors…) — resume callers need ONE refusal shape, not a zoo
        raise ValueError(
            f"checkpoint step {step} in {pathlib.Path(directory)} could not "
            f"be restored ({type(e).__name__}: {e}) — truncated or "
            "corrupted on disk; refusing to resume"
        ) from e
    return _verified(directory, step, restored)


def load_checkpoint(directory: str | pathlib.Path, step: int):
    """Restore the pytree saved at ``step`` (integrity-verified)."""
    with _manager(directory) as mgr:
        return _restore(mgr, directory, step)


def load_checkpoints(directory: str | pathlib.Path, steps):
    """Yield the pytrees saved at each of ``steps`` from ONE open manager.

    Resume replays every per-date increment; constructing a manager per step
    would re-enumerate the whole directory each time (quadratic in walk length
    now that all steps are retained). Each step is integrity-verified; a
    corrupt middle step refuses the whole resume rather than splicing
    garbage into the ledgers.
    """
    with _manager(directory) as mgr:
        for step in steps:
            yield _restore(mgr, directory, step)
