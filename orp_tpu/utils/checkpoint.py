"""Step-indexed pytree checkpointing for preemptible backward-induction runs.

The reference has no disk checkpointing (SURVEY.md §5): its only recovery
mechanisms are Keras best-weight restoration inside one ``fit`` and the warm
start across dates. This module adds the missing piece for long TPU jobs —
persist ``(params, this date's ledger columns)`` after each backward step so a
preempted run resumes at the next date instead of re-simulating/retraining.

Built on ``orbax.checkpoint.CheckpointManager`` (the supported step-management
API: atomic finalisation, latest-step discovery, retention). A *fingerprint*
side-file guards resume compatibility: a directory written by a different run
configuration refuses to resume instead of silently returning stale results.
The fingerprint mechanics live in ``orp_tpu/utils/fingerprint.py``, shared
with the hedge-policy bundles of ``orp_tpu/serve``.
"""

from __future__ import annotations

import pathlib

import jax
import orbax.checkpoint as ocp

from orp_tpu.utils.fingerprint import check_fingerprint

__all__ = [
    "check_fingerprint",
    "save_checkpoint",
    "latest_step",
    "load_checkpoint",
    "load_checkpoints",
]


def _manager(directory: str | pathlib.Path) -> ocp.CheckpointManager:
    # every step is retained: saves are per-date *increments* (one ledger
    # column each), so resume replays all of them — total disk is the ledger
    # size itself, and cumulative write I/O stays O(n_dates * paths) instead of
    # the O(n_dates^2 * paths) that re-saving accumulated state would cost
    return ocp.CheckpointManager(
        pathlib.Path(directory).absolute(),
        options=ocp.CheckpointManagerOptions(max_to_keep=None),
    )


def save_checkpoint(directory: str | pathlib.Path, step: int, state) -> None:
    """Persist ``state`` (any pytree of arrays/scalars) under ``step``."""
    with _manager(directory) as mgr:
        mgr.save(
            step,
            args=ocp.args.PyTreeSave(jax.tree.map(jax.numpy.asarray, state)),
            force=True,
        )
        mgr.wait_until_finished()


def latest_step(directory: str | pathlib.Path) -> int | None:
    """Highest saved step in ``directory``, or None if nothing is saved."""
    if not pathlib.Path(directory).is_dir():
        return None
    with _manager(directory) as mgr:
        return mgr.latest_step()


def load_checkpoint(directory: str | pathlib.Path, step: int):
    """Restore the pytree saved at ``step``."""
    with _manager(directory) as mgr:
        # explicit PyTreeRestore: a fresh manager (new process — exactly the
        # resume case) cannot infer the handler from the directory alone and
        # raises KeyError 'Item "default" ... could not be restored'
        return mgr.restore(step, args=ocp.args.PyTreeRestore())


def load_checkpoints(directory: str | pathlib.Path, steps):
    """Yield the pytrees saved at each of ``steps`` from ONE open manager.

    Resume replays every per-date increment; constructing a manager per step
    would re-enumerate the whole directory each time (quadratic in walk length
    now that all steps are retained).
    """
    with _manager(directory) as mgr:
        for step in steps:
            yield mgr.restore(step, args=ocp.args.PyTreeRestore())
