"""Run-fingerprint side files: one compatibility guard for every artifact dir.

Both persistence layers — per-date training checkpoints
(``orp_tpu/utils/checkpoint.py``) and exported hedge-policy bundles
(``orp_tpu/serve/bundle.py``) — write directories whose contents are only
meaningful under the exact run configuration that produced them. A
``run_fingerprint.txt`` side file records that configuration as a string;
re-opening the directory under a different configuration refuses loudly
instead of silently returning stale or shape-garbled results.

Split out of ``checkpoint.py`` so checkpointing and serving share ONE
definition of write/read/verify, plus the policy-shape helpers the
out-of-sample pipelines use to validate trained params against a fresh
config UP FRONT (a clean ValueError naming both shapes, not a shape error
deep inside the replayed forward).
"""

from __future__ import annotations

import pathlib

from orp_tpu.utils.atomic import atomic_write_text

FINGERPRINT_FILE = "run_fingerprint.txt"


def read_fingerprint(directory: str | pathlib.Path) -> str | None:
    """The fingerprint recorded in ``directory``, or None if none exists."""
    f = pathlib.Path(directory) / FINGERPRINT_FILE
    return f.read_text() if f.exists() else None


def write_fingerprint(directory: str | pathlib.Path, fingerprint: str) -> None:
    # atomic (write-temp-then-rename): a guard file torn by a killed
    # process would make an otherwise-valid directory unopenable — or, if
    # truncation happened to produce a prefix match, silently waive the
    # compatibility check it exists to enforce
    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    atomic_write_text(d / FINGERPRINT_FILE, fingerprint)


def verify_fingerprint(
    directory: str | pathlib.Path, fingerprint: str, *, what: str = "directory"
) -> None:
    """Raise unless ``directory`` records exactly ``fingerprint``.

    A MISSING side file also raises: a directory without provenance cannot be
    proven compatible (bundles always write one; see ``check_fingerprint``
    for the write-on-first-use checkpoint semantics).
    """
    saved = read_fingerprint(directory)
    if saved is None:
        raise ValueError(
            f"{what} {pathlib.Path(directory)} has no {FINGERPRINT_FILE} — "
            "not a directory written by this framework (or partially copied)"
        )
    if saved != fingerprint:
        raise ValueError(
            f"{what} {pathlib.Path(directory)} belongs to a different run config:\n"
            f"  saved:   {saved}\n  current: {fingerprint}\n"
            "use a fresh directory (or delete the old one)"
        )


def check_fingerprint(directory: str | pathlib.Path, fingerprint: str) -> None:
    """Write the run fingerprint on first use; refuse a mismatched directory.

    The checkpoint-resume contract: an empty/new directory adopts the current
    fingerprint, an existing one must match it exactly.
    """
    if read_fingerprint(directory) is None:
        write_fingerprint(directory, fingerprint)
    else:
        verify_fingerprint(directory, fingerprint, what="checkpoint dir")


# ---------------------------------------------------------------------------
# Policy-shape fingerprints (trained per-date params vs a fresh run config)
# ---------------------------------------------------------------------------


def describe_params_by_date(params_by_date) -> str:
    """Canonical shape signature of a per-date params pytree:
    ``"b0:(52, 8), w0:(52, 1, 8), ..."`` (leaf name sorted, leading axis is
    the date axis)."""
    import jax

    leaves = jax.tree_util.tree_leaves_with_path(params_by_date)
    parts = []
    for path, leaf in leaves:
        name = "".join(str(getattr(p, "key", p)) for p in path)
        parts.append(f"{name}:{tuple(leaf.shape)}")
    return ", ".join(sorted(parts))


def describe_model_params(model, n_dates: int) -> str:
    """The signature ``describe_params_by_date`` would produce for per-date
    snapshots of ``model`` over ``n_dates`` rebalance dates — derived purely
    from the model config, no params materialised."""
    sizes = (model.n_features, *model.hidden, model.n_outputs)
    parts = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        parts.append(f"w{i}:{(n_dates, fan_in, fan_out)}")
        parts.append(f"b{i}:{(n_dates, fan_out)}")
    return ", ".join(sorted(parts))


def policy_fingerprint(
    model, n_dates: int, *, dual_mode: str, holdings_combine: str,
    cost_of_capital: float,
) -> str:
    """The full compatibility string for a trained hedge policy: model config,
    date count, per-date param shapes and the value/holdings combine
    semantics. Everything an evaluation needs to agree on; nothing
    path-simulation-specific (the same policy legitimately serves any path
    set)."""
    return (
        f"orp-policy-v1 model={model} n_dates={n_dates} "
        f"dual_mode={dual_mode} holdings_combine={holdings_combine} "
        f"cost_of_capital={cost_of_capital} "
        f"params=[{describe_model_params(model, n_dates)}]"
    )


def verify_policy_compat(name: str, model, n_dates: int, params_by_date) -> None:
    """Up-front guard for the *_oos pipelines and the serving engine: the
    per-date params a trained result/bundle carries must be exactly the
    shapes ``model`` over ``n_dates`` dates would produce. Raises a
    ValueError naming both signatures instead of letting the replayed
    forward fail with an opaque shape error."""
    got = describe_params_by_date(params_by_date)
    want = describe_model_params(model, n_dates)
    if got != want:
        raise ValueError(
            f"{name}: trained policy params do not match this run config:\n"
            f"  trained: [{got}]\n  config:  [{want}]\n"
            "the model head/features or the rebalance-date count differ — "
            "evaluate with the config the policy was trained under"
        )
