"""Matmul-precision control for TPU numerical fidelity.

TPU's default matmul precision rounds `dot_general` inputs to bf16 (~4e-3
relative error). For this framework that default is never the right trade:
the big matmuls are normal-equation Grams (condition number SQUARED — a bf16
Gram wrecked the Gauss-Newton fit outright: v0_network 9.73 vs Black-Scholes
10.39 on v5e, TPU_MEASURE_r4.jsonl / SCALING.md §6b), the CV-OLS products
(whose deterministic rounding leaks a systematic bp-scale shift into the
price — measured −2.4 ± 0.2bp over 8 Owen scrambles), and everything else is
8-to-97-wide — far too small for bf16 MXU tiles to buy speed back.

``highest_matmul_precision`` wraps a function so its body TRACES under
``jax.default_matmul_precision("highest")`` — the config is a trace-time
property baked into the jaxpr (and part of the jit cache key), so decorating
the traced function is exactly equivalent to per-op ``precision=`` arguments.
CPU ignores the setting (always full f32), so the CPU test oracles are
bit-unchanged; TF32-capable GPUs get the same fix as TPU (``highest`` forces
full f32 where the default would lower f32 matmuls to TF32).
"""

from __future__ import annotations

import functools

import jax


def highest_matmul_precision(fn):
    """Decorator: trace ``fn`` under full-f32 matmul precision on TPU."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)

    return wrapped
