"""L6 risk analytics & reporting (SURVEY.md §2 rows 14-15)."""

from orp_tpu.risk.analytics import (
    FanChart,
    HedgeReport,
    build_report,
    discounted_payoff_compare,
    fan_chart,
    holdings_summary,
    residual_pnl_stats,
    var_by_date,
    var_overall,
)
from orp_tpu.risk.asian import asian_call_qmc, geometric_asian_call
from orp_tpu.risk.barrier import down_and_out_call, down_and_out_call_qmc
from orp_tpu.risk.greeks import (
    GreeksResult,
    basket_greeks,
    digital_greeks,
    european_greeks,
    heston_greeks,
)
from orp_tpu.risk.lookback import (
    lookback_call_fixed,
    lookback_call_floating,
    lookback_call_qmc,
    lookback_floating_qmc,
)
from orp_tpu.risk.surface import heston_price_surface, implied_vol, price_surface

__all__ = [
    "FanChart",
    "GreeksResult",
    "asian_call_qmc",
    "basket_greeks",
    "digital_greeks",
    "down_and_out_call",
    "down_and_out_call_qmc",
    "HedgeReport",
    "european_greeks",
    "geometric_asian_call",
    "heston_greeks",
    "heston_price_surface",
    "implied_vol",
    "lookback_call_fixed",
    "lookback_call_floating",
    "lookback_call_qmc",
    "lookback_floating_qmc",
    "price_surface",
    "build_report",
    "discounted_payoff_compare",
    "fan_chart",
    "holdings_summary",
    "residual_pnl_stats",
    "var_by_date",
    "var_overall",
]
