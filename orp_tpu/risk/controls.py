"""Regression-based martingale control variates for risk-neutral QMC pricing.

For ANY risk-neutral path model, the discounted hedge-instrument price
``M_t = e^{-r t} S_t`` is a martingale, so for ANY adapted integrand
``a_t = f(S_t, t)`` the pathwise sum ``sum_t a_t (M_{t+1} - M_t)`` has mean
exactly zero. The learned hedge's phi is ONE such integrand
(``pipelines._attach_cv_price``); this module spans a small per-date basis

    f_j(m) in {1, m, m^2, (m - k)^+, 1{m > k}},  m = S_t / S_0,

optionally augmented with the trained per-date phi, and solves per-date OLS
for the coefficients that minimise the residual variance of the discounted
payoff. Per-date solves are the right decomposition: martingale increments
at different dates are uncorrelated (tower property), so the joint OLS
block-diagonalises; the dates are processed by sequential backfitting
(each date's solve sees the residual left by the dates already processed),
which also absorbs the small in-sample cross-date covariance.

The estimator stays unbiased up to the O(J/n) in-sample coefficient-fit
bias (J = dates x basis columns; ~3e-4 relative to the residual std at 1M
paths x 52 dates) — no option-pricing formula is consulted anywhere, only
the martingale property of the MODEL. This is the variance-reduction layer
that makes the ±1bp north-star claim robust to the QMC seed instead of a
per-seed draw (SCALING.md §3a; the plain hedged-CV estimator's error scale
at 1M paths is ~1-2bp).

No reference analogue: the reference's only price estimators are the
(biased) network prediction and the raw discounted payoff mean
(``European Options.ipynb#20``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from orp_tpu.utils.precision import highest_matmul_precision


@jax.jit
@highest_matmul_precision
def _backfit_scan(y, m_cols, phi_cols, dm_cols, k, ridge):
    """Sequential per-(date, asset) OLS backfitting.

    y: (n,) centred residual start.
    m_cols: (T*A, n) normalised prices per (date, asset) slot.
    phi_cols: (T*A, n) trained holdings — or a (1, n) zero row broadcast-
        compatible sentinel when absent (a zero column is ridge-harmless).
    dm_cols: (T*A, n) discounted-price martingale increments.
    Returns the residual after subtracting every fitted control.

    Traces under full-f32 matmul precision (``highest_matmul_precision``):
    TPU's default bf16 rounding of the Gram/projection products is
    deterministic (non-mean-zero) and leaks a systematic bp-scale shift into
    ``mean(resid)`` — the exact quantity this estimator exists to pin to
    sub-bp accuracy (measured −2.4 ± 0.2bp over 8 Owen scrambles on v5e,
    SCALING.md §6b). The products are (n, J<=6)-sized: full-f32 is free.
    """
    use_phi = phi_cols.shape[0] == m_cols.shape[0]

    def body(y, xs):
        m, phi, d = xs
        cols = [jnp.ones_like(m), m, m * m,
                jnp.maximum(m - k, 0.0), (m > k).astype(m.dtype)]
        if use_phi:
            cols.append(phi)
        X = jnp.stack(cols, axis=-1) * d[:, None]   # (n, J) mean-0 columns
        n = X.shape[0]
        # whiten columns to unit second moment: the basis is heavily
        # degenerate at early dates (m ~ constant makes 1/m/m^2 collinear and
        # the kink/indicator columns vanish) — relative ridge on the whitened
        # Gram keeps the solve finite with degenerate columns pinned to
        # beta ~ 0 instead of blowing up
        sd = jnp.sqrt(jnp.mean(X * X, axis=0))
        sd = jnp.where(sd > 0, sd, 1.0)
        Xn = X / sd
        g = Xn.T @ Xn / n
        c = Xn.T @ y / n
        # spectral pseudo-inverse: project out near-null directions of the
        # whitened Gram entirely (f32-safe — a plain ridge solve at f32 eps
        # still blows up on the rank-1 date-0 Gram)
        w, v = jnp.linalg.eigh(g)
        tol = ridge * jnp.max(jnp.abs(w))
        winv = jnp.where(w > tol, 1.0 / jnp.where(w > tol, w, 1.0), 0.0)
        beta = v @ (winv * (v.T @ c))
        y = y - Xn @ beta
        return y, None

    if not use_phi:
        phi_cols = jnp.zeros_like(m_cols)
    y, _ = jax.lax.scan(body, y, (m_cols, phi_cols, dm_cols))
    return y


def martingale_ols_price(
    s: jax.Array,
    payoff: jax.Array,
    r: float,
    times: jax.Array,
    *,
    strike_over_s0: float = 1.0,
    phi: jax.Array | None = None,
    ridge: float = 1e-5,
) -> tuple[float, float]:
    """OLS-martingale-controlled price: ``(v0, residual_std)``.

    ``s``: (n, T+1) hedge-instrument paths at the rebalance knots — or
    (n, T+1, A) for several instruments (each asset contributes its own
    basis block built from its own normalised price).
    ``payoff``: (n,) terminal payoff; ``times``: (T+1,) knot times.
    ``phi``: optional (n, T[, A]) trained holdings, added as a basis column.
    """
    if s.ndim == 2:
        s = s[:, :, None]
        phi = None if phi is None else phi[:, :, None]
    n, n_knots, n_assets = s.shape
    dtype = s.dtype
    disc = jnp.exp(-r * jnp.asarray(times, dtype))
    m_disc = disc[None, :, None] * s                       # (n, T+1, A)
    dm = m_disc[:, 1:] - m_disc[:, :-1]                    # (n, T, A)
    m_norm = s[:, :-1] / s[:, :1]                          # vs each asset's S_0

    # (T*A, n) per-(date, asset) slot ordering
    to_cols = lambda a: jnp.moveaxis(a, 0, -1).reshape(-1, n)
    m_cols = to_cols(m_norm)
    dm_cols = to_cols(dm)
    phi_cols = to_cols(phi.astype(dtype)) if phi is not None else (
        jnp.zeros((1, n), dtype)  # sentinel row: length mismatch => no phi col
    )

    y = disc[-1] * payoff.astype(dtype)
    v0_plain = jnp.mean(y)
    resid = _backfit_scan(
        y - v0_plain, m_cols, phi_cols, dm_cols,
        jnp.asarray(strike_over_s0, dtype), jnp.asarray(ridge, dtype),
    )
    # every control column has EXACT zero expectation, so the estimator is
    # mean(y) minus the (mean-zero) fitted controls: the residual's sample
    # mean carries exactly that correction
    v0 = float(v0_plain + jnp.mean(resid))
    return v0, float(jnp.std(resid))
