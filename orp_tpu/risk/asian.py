"""Arithmetic-Asian options with the exact geometric-Asian control variate.

The reference prices only terminal-payoff claims. Path-dependent averages are
the natural next ask, and under GBM they come with a classical free lunch: the
GEOMETRIC average of lognormals is itself lognormal, so the geometric-Asian
call has an exact Black-Scholes-style closed form — and it is ~0.99-correlated
with the arithmetic payoff. Using it as a control variate
(``price = mean(arith) + (geo_closed_form - mean(geo))``) removes almost all
of the Monte-Carlo variance: measured ~29x std reduction at the default
config (PARITY.md), i.e. ~1.5 extra digits of accuracy from the same paths.

Closed form (discrete equally spaced averaging over t_1..t_m):
``log G = log s0 + (r - sigma^2/2) * tbar + (sigma/m) * sum_i W(t_i)`` with
``tbar = mean(t_i)`` and ``Var[(1/m) sum W(t_i)] = (1/m^2) sum_{ij}
min(t_i, t_j)`` — a plain lognormal, priced by the usual two-term formula.

The averaging grid rides the scan's stored knots (``store_every``), so the
whole pricer is one simulation + O(m^2) host arithmetic for the closed form.
Memory note: the geometric leg needs ``log(S_t/s0)`` — a device log of a
value near 1, where f32 ``log`` is well-conditioned (the SCALING.md §6d
defect was ``log(100)``, 74 ulps out; log1p-range inputs are exact to ~1
ulp), so no-device-log policy is not violated in spirit: no CONSTANT is
seeded through a transcendental.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from orp_tpu.sde.grid import TimeGrid
from orp_tpu.sde.kernels import simulate_gbm_log
from orp_tpu.utils.black_scholes import _N


def geometric_asian_call(
    s0: float, k: float, r: float, sigma: float, T: float, n_avg: int
) -> float:
    """Exact price of the discretely-monitored geometric-Asian call
    (equally spaced t_i = i*T/m, i=1..m). Host f64 oracle."""
    m = n_avg
    times = [T * i / m for i in range(1, m + 1)]
    tbar = sum(times) / m
    # Var[(1/m) sum W(t_i)] = (1/m^2) * sum_ij min(t_i, t_j)
    var_w = sum(min(ti, tj) for ti in times for tj in times) / (m * m)
    mu_g = math.log(s0) + (r - 0.5 * sigma * sigma) * tbar
    sd_g = sigma * math.sqrt(var_w)
    if sd_g == 0.0:  # sigma=0: deterministic average, pure intrinsic
        return math.exp(-r * T) * max(math.exp(mu_g) - k, 0.0)
    d1 = (mu_g - math.log(k) + sd_g * sd_g) / sd_g
    d2 = d1 - sd_g
    fwd_g = math.exp(mu_g + 0.5 * sd_g * sd_g)
    return math.exp(-r * T) * (fwd_g * _N(d1) - k * _N(d2))


def asian_call_qmc(
    n_paths: int,
    s0: float,
    k: float,
    r: float,
    sigma: float,
    T: float,
    *,
    n_avg: int = 52,
    steps_per_avg: int = 7,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jnp.ndarray | None = None,
    dtype=jnp.float32,
) -> dict[str, float]:
    """Arithmetic-Asian call by Sobol-QMC with the geometric control variate.

    Returns both the plain estimator and the controlled one (``price``), with
    iid-diagnostic SEs; ``geo_closed`` / ``geo_sample`` expose the CV pieces.
    """
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    grid = TimeGrid(T, n_avg * steps_per_avg)
    s = simulate_gbm_log(
        indices, grid, s0, r, sigma, seed=seed, scramble=scramble,
        store_every=steps_per_avg, dtype=dtype,
    )[:, 1:]  # (n, m) at the averaging dates
    disc = math.exp(-r * T)
    arith = disc * jnp.maximum(jnp.mean(s, axis=1) - k, 0.0)
    # geometric leg: log of S_t/s0 ~ O(1) ratios (well-conditioned f32 log)
    geo = jnp.asarray(s0, dtype) * jnp.exp(
        jnp.mean(jnp.log(s / jnp.asarray(s0, dtype)), axis=1)
    )
    geo_pay = disc * jnp.maximum(geo - k, 0.0)
    geo_closed = geometric_asian_call(s0, k, r, sigma, T, n_avg)

    n = arith.shape[0]
    plain = float(jnp.mean(arith))
    geo_sample = float(jnp.mean(geo_pay))
    controlled = plain + (geo_closed - geo_sample)  # beta = 1 control
    resid_std = float(jnp.std(arith - geo_pay))
    return {
        "price": controlled,
        "se": resid_std / math.sqrt(n),
        "plain": plain,
        "se_plain": float(jnp.std(arith)) / math.sqrt(n),
        "geo_closed": geo_closed,
        "geo_sample": geo_sample,
        "n_paths": int(n),
        "n_avg": n_avg,
    }
