"""Lookback options: exact bridge-maximum sampling vs the closed form.

Companion to ``risk/barrier.py``: instead of weighting by the bridge
CROSSING probability, the running maximum itself is SAMPLED exactly — for a
Brownian bridge between log-knots ``x_i, x_{i+1}`` with variance
``s^2 = sigma^2 dt``, the conditional maximum has the closed inverse-CDF

    M_i = (x_i + x_{i+1} + sqrt((x_{i+1} - x_i)^2 - 2 s^2 ln U_i)) / 2,

so one extra uniform per interval turns the stored knots into the EXACT
continuous-time running maximum (in law). A fixed-strike lookback call
``max(S_max - K, 0)`` priced this way is unbiased from any monitoring grid,
while the naive knot-max is biased LOW by the missed intra-interval maxima.

The bridge uniforms ride Sobol dimensions BEYOND the path dimensions —
the same index-addressed point set, one dimension per monitoring interval
(dims ``n_steps .. n_steps + m - 1``), so the whole estimator stays a pure
function of (indices, seed).

Oracle: the Conze-Viswanathan closed form for the continuously-monitored
fixed-strike lookback call (host f64; both K >= S0 and K < S0 branches).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from orp_tpu.qmc.sobol import _N_DIMS, sobol_uniform
from orp_tpu.sde.grid import TimeGrid
from orp_tpu.sde.kernels import scan_sde
from orp_tpu.utils.black_scholes import _N


def lookback_call_fixed(
    s0: float, k: float, r: float, sigma: float, T: float
) -> float:
    """Continuously-monitored fixed-strike lookback call (Conze-
    Viswanathan), running max observed from t=0 (M_0 = S_0)."""
    if r <= 0.0:
        raise ValueError("the Conze-Viswanathan form here assumes r > 0")
    if k < s0:
        # standard decomposition: payoff = (M - K)^+ = (S0 - K) + (M - S0)^+
        # since M >= S0 >= K always
        return math.exp(-r * T) * (s0 - k) + lookback_call_fixed(
            s0, s0, r, sigma, T
        )
    if sigma == 0.0:  # deterministic path: max over [0,T] is s0*e^{rT} (r>0)
        return math.exp(-r * T) * max(s0 * math.exp(r * T) - k, 0.0)
    sq = sigma * math.sqrt(T)
    d1 = (math.log(s0 / k) + (r + 0.5 * sigma * sigma) * T) / sq
    d2 = d1 - sq
    beta = 2.0 * r / (sigma * sigma)
    # C = S0 N(d1) - K e^{-rT} N(d2)
    #     + (S0/beta) [N(d1) - e^{-rT} (S0/K)^{-beta} N(d1 - beta sq)]
    # (verified against the bridge-max sampler: 16.80 closed vs
    # 16.81 +/- 0.08 QMC at the K=110 config)
    nphi = _N(d1 - beta * sq)
    if beta * sq > 40.0 or nphi == 0.0:
        # sigma -> 0 and deep-OTM tails: the Gaussian factor N(d1 - beta*sq)
        # decays like exp(-(d1 - beta*sq)^2/2), crushing the power term —
        # the product is 0 to all precision while (s0/k)**(-beta) alone
        # would overflow (beta*ln(k/s0) > 709 is reachable with
        # beta*sq <= 40, e.g. sigma=0.01, k/s0 > 2.03)
        reflect = 0.0
    else:
        # log space: exp of the summed exponents instead of the raw power,
        # so no intermediate overflows for strikes many sigma*sqrt(T) out
        reflect = math.exp(-r * T - beta * math.log(s0 / k)
                           + math.log(nphi))
    return (s0 * _N(d1) - k * math.exp(-r * T) * _N(d2)
            + (s0 / beta) * (_N(d1) - reflect))


def _bridge_extreme_knots(
    n_paths, r, sigma, T, n_monitor, steps_per_monitor, bridge, sign,
    seed, scramble, indices, dtype,
):
    """Shared sampler: (log-knots x (n, m+1), log-extreme x_ext (n,)) where
    ``sign=+1`` samples the exact per-interval bridge MAXIMUM and ``sign=-1``
    the minimum (``bridge=False``: the naive knot extreme)."""
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    n_steps = n_monitor * steps_per_monitor
    if bridge and n_steps + n_monitor > _N_DIMS:
        # JAX gathers CLAMP out-of-bounds rows — without this check every
        # overrunning bridge interval would silently share dimension 16383
        raise ValueError(
            f"n_steps + n_monitor = {n_steps + n_monitor} exceeds the "
            f"{_N_DIMS}-dimension Sobol table (bridge uniforms ride the "
            "dims past the path dims)"
        )
    grid = TimeGrid(T, n_steps)
    # log-return knots straight from the scan (the same recurrence
    # simulate_gbm_log wraps) — no price-space exp/log round trip
    sdt = jnp.asarray(grid.dt, dtype) ** 0.5
    c0 = (r - 0.5 * sigma * sigma) * grid.dt

    def step(acc, z, t, dt):
        return acc + c0 + sigma * sdt * z[:, 0]

    _, x = scan_sde(
        step, jnp.zeros(indices.shape, dtype), lambda a: a, indices, grid,
        1, seed, scramble=scramble, store_every=steps_per_monitor,
        dtype=dtype,
    )  # (n, m+1) incl. t=0
    extreme = jnp.max if sign > 0 else jnp.min
    if bridge:
        # one extra Sobol dim per monitoring interval, PAST the path dims
        dims = n_steps + jnp.arange(n_monitor, dtype=jnp.uint32)
        u = sobol_uniform(indices, dims, seed, scramble=scramble,
                          dtype=dtype)  # (n, m) in (0, 1)
        s2 = jnp.asarray(sigma * sigma * (T / n_monitor), dtype)
        d = x[:, 1:] - x[:, :-1]
        m_int = 0.5 * (x[:, :-1] + x[:, 1:]
                       + sign * jnp.sqrt(d * d - 2.0 * s2 * jnp.log(u)))
        x_ext = extreme(m_int, axis=1)
    else:
        x_ext = extreme(x, axis=1)
    return x, x_ext


def lookback_call_qmc(
    n_paths: int,
    s0: float,
    k: float,
    r: float,
    sigma: float,
    T: float,
    *,
    n_monitor: int = 52,
    steps_per_monitor: int = 1,
    bridge: bool = True,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jnp.ndarray | None = None,
    dtype=jnp.float32,
) -> dict[str, float]:
    """Fixed-strike lookback call by Sobol-QMC. ``bridge=True`` samples the
    exact per-interval bridge maximum (unbiased for continuous monitoring);
    ``bridge=False`` is the naive knot-max, kept to measure its low bias."""
    _, x_max = _bridge_extreme_knots(
        n_paths, r, sigma, T, n_monitor, steps_per_monitor, bridge, +1.0,
        seed, scramble, indices, dtype,
    )
    s_max = jnp.asarray(s0, dtype) * jnp.exp(x_max)
    v = math.exp(-r * T) * jnp.maximum(s_max - k, 0.0)
    n = v.shape[0]
    return {
        "price": float(jnp.mean(v)),
        "se": float(jnp.std(v)) / math.sqrt(n),
        "mean_smax": float(jnp.mean(s_max)),
        "n_paths": int(n),
        "n_monitor": n_monitor,
    }


def lookback_call_floating(
    s0: float, r: float, sigma: float, T: float
) -> float:
    """Continuously-monitored FLOATING-strike lookback call
    ``S_T - min S`` (Goldman-Sosin-Gatto), min observed from t=0."""
    if r <= 0.0:
        raise ValueError("the Goldman-Sosin-Gatto form here assumes r > 0")
    sq = sigma * math.sqrt(T)
    if sigma == 0.0:
        # deterministic path: min is s0 (r>0), payoff s0(e^{rT}-1)
        return s0 * (1.0 - math.exp(-r * T))
    a1 = (r + 0.5 * sigma * sigma) * math.sqrt(T) / sigma
    a2 = a1 - sq
    beta = 2.0 * r / (sigma * sigma)
    # C = S0 N(a1) - S0 e^{-rT} N(a2) + (S0/beta)(e^{-rT} N(a2) - N(-a1)):
    # GSG with m0 = S0, where the reflected-term argument
    # -a1 + (2r/sigma)sqrt(T) collapses to a2 and (S0/m0)^{-beta} to 1.
    # The argument SIGN was pinned by the bridge-MIN sampler cross-check
    # (21.89 closed vs 21.8905 +/- 0.075 QMC) — the same discipline that
    # caught the fixed-strike exponent error
    return (s0 * _N(a1) - s0 * math.exp(-r * T) * _N(a2)
            + (s0 / beta) * (math.exp(-r * T) * _N(a2) - _N(-a1)))


def lookback_floating_qmc(
    n_paths: int,
    s0: float,
    r: float,
    sigma: float,
    T: float,
    *,
    n_monitor: int = 52,
    steps_per_monitor: int = 1,
    bridge: bool = True,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jnp.ndarray | None = None,
    dtype=jnp.float32,
) -> dict[str, float]:
    """Floating-strike lookback call ``S_T - min S`` by Sobol-QMC with the
    exact per-interval bridge MINIMUM (the reflection of the max sampler:
    ``(x_i + x_{i+1} - sqrt(d^2 - 2 s^2 ln U)) / 2``)."""
    x, x_min = _bridge_extreme_knots(
        n_paths, r, sigma, T, n_monitor, steps_per_monitor, bridge, -1.0,
        seed, scramble, indices, dtype,
    )
    s_t = jnp.asarray(s0, dtype) * jnp.exp(x[:, -1])
    s_min = jnp.asarray(s0, dtype) * jnp.exp(x_min)
    v = math.exp(-r * T) * (s_t - s_min)  # always >= 0
    n = v.shape[0]
    return {
        "price": float(jnp.mean(v)),
        "se": float(jnp.std(v)) / math.sqrt(n),
        "mean_smin": float(jnp.mean(s_min)),
        "n_paths": int(n),
        "n_monitor": n_monitor,
    }
