"""Barrier options: Brownian-bridge-corrected QMC vs the reflection oracle.

The reference knows only terminal payoffs. Barrier claims add the classic
discrete-monitoring trap: checking the barrier at the stored knots misses
intra-interval crossings, biasing a down-and-out price HIGH by O(1/sqrt(m)).
Under GBM the log-price is a Brownian motion, so the crossing probability of
each interval CONDITIONAL on its endpoints is exact —
``exp(-2 (x_i - h)(x_{i+1} - h) / (sigma^2 dt))`` for the Brownian bridge —
and weighting each path by its interval survival products removes the
discretization bias entirely (Beaglehole-Dybvig-Zhou): the estimator is
unbiased for the CONTINUOUS barrier from any monitoring grid.

Oracle: the closed-form reflection-principle price of the continuous
down-and-out call (Merton/Hull; ``down_and_out_call``), host f64.

TPU notes: the survival weight is a product over stored knots — one fused
elementwise pass over the (n_paths, m) array, O(paths) memory via
``store_every``; everything shards over the path axis. The only device log
is ``log(S/H)`` of O(1) ratios, where f32 log is tight (the SCALING.md §6d
defect was a large-argument CONSTANT through ``log``; no such constant is
seeded here).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from orp_tpu.sde.grid import TimeGrid
from orp_tpu.sde.kernels import simulate_gbm_log
from orp_tpu.utils.black_scholes import _N, bs_call


def down_and_out_call(
    s0: float, k: float, h: float, r: float, sigma: float, T: float
) -> float:
    """Continuous-barrier down-and-out call, reflection principle (H <= K).

    ``c_do = c_bs - c_di`` with the down-and-in part priced off the
    reflected process; requires ``h <= k`` (the standard regime) and
    ``h < s0`` (otherwise already knocked out -> 0).
    """
    if h >= s0:
        return 0.0
    if h <= 0.0:
        return bs_call(s0, k, r, sigma, T)[0]
    if h > k:
        raise ValueError(f"down_and_out_call needs h <= k, got h={h} k={k}")
    if sigma == 0.0:  # deterministic path s0*e^{rt}: monotone, so the
        # running minimum is at an endpoint; knocked out iff it touches h
        if min(s0, s0 * math.exp(r * T)) <= h:
            return 0.0
        return math.exp(-r * T) * max(s0 * math.exp(r * T) - k, 0.0)
    lam = (r + 0.5 * sigma * sigma) / (sigma * sigma)
    sq = sigma * math.sqrt(T)
    y = math.log(h * h / (s0 * k)) / sq + lam * sq
    c_di = (s0 * (h / s0) ** (2.0 * lam) * _N(y)
            - k * math.exp(-r * T) * (h / s0) ** (2.0 * lam - 2.0)
            * _N(y - sq))
    return bs_call(s0, k, r, sigma, T)[0] - c_di


def down_and_out_call_qmc(
    n_paths: int,
    s0: float,
    k: float,
    h: float,
    r: float,
    sigma: float,
    T: float,
    *,
    n_monitor: int = 52,
    steps_per_monitor: int = 1,
    bridge: bool = True,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jnp.ndarray | None = None,
    dtype=jnp.float32,
) -> dict[str, float]:
    """Down-and-out call by Sobol-QMC. ``bridge=True`` multiplies each path
    by its exact per-interval bridge survival probability (unbiased for the
    continuous barrier); ``bridge=False`` is the naive knot-check, kept to
    measure the discrete-monitoring bias it suffers."""
    if h >= s0:
        # already knocked out — the same answer the closed form gives,
        # without burning a simulation
        return {"price": 0.0, "se": 0.0, "knockout_frac": 1.0,
                "n_paths": int(n_paths), "n_monitor": n_monitor}
    if sigma == 0.0:
        # Deterministic path s0*e^{rt}: monotone, so the running minimum sits
        # at an endpoint — no simulation, and no 0/0 in the bridge weight
        # exponent (which divides by sigma^2 dt).
        knocked = min(s0, s0 * math.exp(r * T)) <= h
        price = 0.0 if knocked else (
            math.exp(-r * T) * max(s0 * math.exp(r * T) - k, 0.0))
        return {"price": price, "se": 0.0,
                "knockout_frac": 1.0 if knocked else 0.0,
                "n_paths": int(n_paths), "n_monitor": n_monitor}
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    grid = TimeGrid(T, n_monitor * steps_per_monitor)
    s = simulate_gbm_log(
        indices, grid, s0, r, sigma, seed=seed, scramble=scramble,
        store_every=steps_per_monitor, dtype=dtype,
    )  # (n, m+1) incl. t=0
    alive = jnp.all(s > h, axis=1)  # knot-level knockout
    payoff = jnp.maximum(s[:, -1] - k, 0.0)
    if bridge:
        x = jnp.log(s / jnp.asarray(h, dtype))  # O(1) ratios: f32-tight
        dt_m = T / n_monitor
        cross = jnp.exp(-2.0 * x[:, :-1] * x[:, 1:]
                        / (sigma * sigma * dt_m))
        survive = jnp.prod(1.0 - jnp.minimum(cross, 1.0), axis=1)
        weight = jnp.where(alive, survive, 0.0)
    else:
        weight = alive.astype(dtype)
    v = math.exp(-r * T) * payoff * weight
    n = v.shape[0]
    return {
        "price": float(jnp.mean(v)),
        "se": float(jnp.std(v)) / math.sqrt(n),
        "knockout_frac": float(1.0 - jnp.mean(weight)),
        "n_paths": int(n),
        "n_monitor": n_monitor,
    }
