"""Price/implied-vol surfaces from a single Sobol path set.

The reference prices exactly one (strike, maturity) point per run (its
notebooks hard-code K = S0 and one horizon). Here the simulation already
stores every rebalance-grid knot, so ONE path set prices the whole maturity
axis for free, and the strike axis is a per-strike payoff mean over the same
paths — an (n_maturities × n_strikes) European surface from one 1M-path
simulation, then inverted to Black-Scholes implied vols by a vectorized
Newton iteration (closed-form vega) that runs as one jitted program over the
whole grid.

Under flat-vol GBM dynamics the recovered smile must be flat at the input
sigma — that identity (surface -> IV -> sigma round-trip) is the oracle
pinned in ``tests/test_surface.py``. With Heston paths the same machinery
produces the model's skew (no oracle needed; the smile IS the output).

TPU notes: strikes are swept with ``lax.map`` so the (n_paths, m, K) payoff
tensor never materialises — each strike is a fused subtract/max/mean over
the stored (n_paths, m) knots. The Newton solve is elementwise over the
grid; everything shards over a ``("paths",)`` mesh up to the final means.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from orp_tpu.sde.grid import TimeGrid
from orp_tpu.sde.kernels import heston_sim_fn, simulate_gbm_log


@functools.partial(jax.jit, static_argnames=("kind",))
def _surface_from_paths(s, times, strikes, r, kind):
    """(m, K) discounted payoff means from stored knots ``s``: (n, m)."""
    disc = jnp.exp(-r * times)  # (m,)
    sign = 1.0 if kind == "call" else -1.0

    def one_strike(k):
        pay = jnp.maximum(sign * (s - k), 0.0)  # (n, m), fused
        return disc * jnp.mean(pay, axis=0)     # (m,)

    return jax.lax.map(one_strike, strikes).T  # (m, K)


@functools.partial(jax.jit, static_argnames=("kind", "n_iter"))
def implied_vol(
    prices, s0, strikes, times, r, *, kind: str = "call", n_iter: int = 25,
    sigma0: float = 0.3,
):
    """Black-Scholes implied vol over a (m, K) price grid by vectorized
    Newton with the closed-form vega. Entries whose price sits outside the
    no-arbitrage band (below intrinsic-forward or above the s0/K bound)
    return NaN."""
    prices = jnp.asarray(prices)
    k = jnp.asarray(strikes)[None, :]
    t = jnp.asarray(times)[:, None]
    disc = jnp.exp(-r * t)
    sign = 1.0 if kind == "call" else -1.0
    lower = jnp.maximum(sign * (s0 - k * disc), 0.0)  # forward intrinsic
    upper = jnp.where(sign > 0, s0, k * disc)
    # time value below ~1e-5 of spot scale is not invertible (vega ~ 0 and
    # the price sits inside its own QMC/f32 noise of the intrinsic floor)
    eps = 1e-5 * s0
    ok = (prices > lower + eps) & (prices < upper - eps) & (t > 0)

    sqrt_t = jnp.sqrt(jnp.maximum(t, 1e-12))
    inv_sqrt2pi = 0.3989422804014327

    def newton(sig, _):
        d1 = (jnp.log(s0 / k) + (r + 0.5 * sig * sig) * t) / (sig * sqrt_t)
        d2 = d1 - sig * sqrt_t
        nd1 = jax.scipy.stats.norm.cdf(sign * d1)
        nd2 = jax.scipy.stats.norm.cdf(sign * d2)
        model = sign * (s0 * nd1 - k * disc * nd2)
        vega = s0 * sqrt_t * inv_sqrt2pi * jnp.exp(-0.5 * d1 * d1)
        step = (model - prices) / jnp.maximum(vega, 1e-8)
        # damped, positivity-preserving update
        return jnp.clip(sig - jnp.clip(step, -0.5, 0.5), 1e-4, 5.0), ()

    sig0 = jnp.full(prices.shape, sigma0, prices.dtype)
    sig, _ = jax.lax.scan(newton, sig0, None, length=n_iter)
    return jnp.where(ok, sig, jnp.nan)


def price_surface(
    n_paths: int,
    s0: float,
    r: float,
    sigma: float,
    strikes,
    T: float,
    *,
    kind: str = "call",
    n_maturities: int = 52,
    steps_per_maturity: int = 7,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jax.Array | None = None,
    with_iv: bool = True,
    dtype=jnp.float32,
) -> dict[str, jax.Array]:
    """European price (and implied-vol) surface over ``strikes`` ×
    ``n_maturities`` equally spaced maturities, from ONE GBM-Sobol path set.
    Returns ``{"times", "strikes", "prices", "iv"?}`` with prices of shape
    (n_maturities, n_strikes)."""
    indices, strikes, grid = _surface_prelude(
        kind, indices, n_paths, strikes, T, n_maturities,
        steps_per_maturity, dtype,
    )
    s = simulate_gbm_log(
        indices, grid, s0, r, sigma, seed=seed, scramble=scramble,
        store_every=steps_per_maturity, dtype=dtype,
    )[:, 1:]  # (n, m) — drop the t=0 knot
    return _assemble_surface(s, s0, strikes, r, T, n_maturities, kind,
                             with_iv, dtype)


def heston_price_surface(
    n_paths: int,
    s0: float,
    r: float,
    strikes,
    T: float,
    *,
    v0: float,
    kappa: float,
    theta: float,
    xi: float,
    rho: float,
    kind: str = "call",
    n_maturities: int = 52,
    steps_per_maturity: int = 7,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jax.Array | None = None,
    with_iv: bool = True,
    scheme: str = "qe",
    dtype=jnp.float32,
) -> dict[str, jax.Array]:
    """The same one-simulation surface under HESTON dynamics: here the
    Black-Scholes inversion produces a real SKEW (negative spot-vol
    correlation tilts the smile), not a flat line — the surface tool is
    model-free, only the path generator changes. Validated node-by-node
    against the Gil-Pelaez characteristic-function oracle
    (``tests/test_surface.py``). ``scheme``: "qe" (Andersen QE-M, default
    since r5 — per-step moment matching removes the Euler fine-step bias
    at every maturity knot simultaneously) or "euler" (full-truncation)."""
    indices, strikes, grid = _surface_prelude(
        kind, indices, n_paths, strikes, T, n_maturities,
        steps_per_maturity, dtype,
    )
    sim = heston_sim_fn(scheme)
    traj = sim(
        indices, grid, s0=s0, mu=r, v0=v0, kappa=kappa, theta=theta, xi=xi,
        rho=rho, seed=seed, scramble=scramble,
        store_every=steps_per_maturity, dtype=dtype,
    )
    return _assemble_surface(traj["S"][:, 1:], s0, strikes, r, T,
                             n_maturities, kind, with_iv, dtype)


def _surface_prelude(kind, indices, n_paths, strikes, T, n_maturities,
                     steps_per_maturity, dtype):
    """Shared argument validation/setup for every dynamics variant."""
    if kind not in ("call", "put"):
        raise ValueError(f"kind must be 'call' or 'put', got {kind!r}")
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    return (indices, jnp.asarray(strikes, dtype),
            TimeGrid(T, n_maturities * steps_per_maturity))


def _assemble_surface(s, s0, strikes, r, T, n_maturities, kind, with_iv,
                      dtype):
    """Shared epilogue: (n, m) stored knots -> price (+ IV) surface dict —
    ONE copy of the maturity grid / inversion contract for all dynamics."""
    times = (jnp.arange(1, n_maturities + 1, dtype=dtype)
             * jnp.asarray(T / n_maturities, dtype))
    prices = _surface_from_paths(s, times, strikes, r, kind)
    out = {"times": times, "strikes": strikes, "prices": prices}
    if with_iv:
        out["iv"] = implied_vol(prices, s0, strikes, times, r, kind=kind)
    return out
