"""Risk analytics: VaR ledgers, residual P&L, fan charts, holdings aggregation.

TPU re-design of the reference's pandas/seaborn reporting layer:

- per-step VaR quantile prints              ``Replicating_Portfolio.py:122``
- VaR-over-time aggregation (groupby+quantile) ``Multi Time Step.ipynb#23``,
  ``European Options.ipynb#16``
- residual P&L at T scatter/stats           ``European Options.ipynb#15``
- portfolio-value fan chart bands           ``Euro#20``, ``Multi#26``
- phi/psi aggregation to the t=0 answer ×ADJUSTMENT_FACTOR
  ``Replicating_Portfolio.py:229-235``, ``Multi#25``, ``Euro#18``
- portfolio value vs discounted payoffs (P_E_Values ledger) ``RP.py:227``

Everything here is plain arrays under jit — no pandas in the hot path; the
quantile reductions go through ``orp_tpu.parallel.quantiles`` so they stay
device-side and sharding-aware. Optional pandas frames at the edge are provided
by ``to_frames`` for notebook parity.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from orp_tpu.parallel.quantiles import quantile

DEFAULT_VAR_QS = (0.98, 0.99, 0.995)
DEFAULT_FAN_QS = (0.01, 0.05, 0.10, 0.90, 0.95, 0.99)


def _columnwise_quantiles(x: jax.Array, qs, method: str) -> np.ndarray:
    """Quantiles per time column of ``x (n_paths, n_cols)`` -> ``(n_cols, n_q)``,
    as one device dispatch (not a per-column host loop)."""
    qs_arr = jnp.asarray(qs, x.dtype)
    if method == "sort":
        return np.asarray(jnp.quantile(x, qs_arr, axis=0).T)
    out = jax.vmap(lambda col: quantile(col, qs_arr, method=method), in_axes=1)(x)
    return np.asarray(out)


def var_by_date(
    residuals: jax.Array, qs=DEFAULT_VAR_QS, method: str = "sort"
) -> np.ndarray:
    """Per-rebalance-date VaR quantiles of replication residuals.

    ``residuals`` is ``(n_paths, n_dates)`` (the ``VaR_HV`` ledger,
    RP.py:114-121); returns ``(n_dates, len(qs))`` — the ``groupby(level=0)`` +
    quantile aggregation of ``Multi Time Step.ipynb#23``.
    """
    return _columnwise_quantiles(residuals, qs, method)


def var_overall(residuals: jax.Array, qs=DEFAULT_VAR_QS, method: str = "sort") -> np.ndarray:
    """Pooled VaR over all dates+paths (``European Options.ipynb#16`` overall print)."""
    return np.asarray(quantile(residuals.reshape(-1), qs, method=method))


@dataclasses.dataclass
class FanChart:
    """Quantile bands of portfolio value over time (``Euro#20`` chart data)."""

    qs: np.ndarray      # (n_q,)
    bands: np.ndarray   # (n_knots, n_q)
    mean: np.ndarray    # (n_knots,)


def fan_chart(values: jax.Array, qs=DEFAULT_FAN_QS, method: str = "sort") -> FanChart:
    """Per-knot quantile bands + mean of the ``values`` matrix ``(n_paths, n_knots)``."""
    return FanChart(
        qs=np.asarray(qs),
        bands=_columnwise_quantiles(values, qs, method),
        mean=np.asarray(jnp.mean(values, axis=0)),
    )


def residual_pnl_stats(residual: jax.Array) -> dict[str, float]:
    """Mean/std/min/max of terminal hedge residuals (``Euro#15(out)`` stats)."""
    return {
        "mean": float(jnp.mean(residual)),
        "std": float(jnp.std(residual)),
        "min": float(jnp.min(residual)),
        "max": float(jnp.max(residual)),
    }


def holdings_summary(
    phi: jax.Array, psi: jax.Array, adjustment_factor: float = 1.0
) -> dict[str, np.ndarray]:
    """Per-date mean holdings ×``adjustment_factor`` and the t=0 answer.

    The reference's final aggregation (``Replicating_Portfolio.py:229-235``):
    pandas ``groupby(T, Type).mean`` of the Phi_Psi ledger scaled by
    ``ADJUSTMENT_FACTOR`` (= N·P for pensions, S0 for options). Here a plain
    per-column mean.
    """
    phi_mean = np.asarray(jnp.mean(phi, axis=0)) * adjustment_factor
    psi_mean = np.asarray(jnp.mean(psi, axis=0)) * adjustment_factor
    return {
        "phi_by_date": phi_mean,
        "psi_by_date": psi_mean,
        "phi0": float(phi_mean[0]),
        "psi0": float(psi_mean[0]),
    }


def discounted_payoff_compare(
    values: jax.Array,
    terminal_payoff: jax.Array,
    r: float,
    times: jax.Array,
) -> dict[str, np.ndarray]:
    """Portfolio value vs discounted expected payoff per knot (P_E_Values ledger,
    RP.py:227; the E^Q/E^P reference lines of the ``Euro#20`` fan chart).

    ``times`` are the knot times ``(n_knots,)``; discounting uses ``exp(-r (T - t))``.
    """
    times = jnp.asarray(times)
    T = times[-1]
    e_payoff = jnp.mean(terminal_payoff)
    disc = jnp.exp(-r * (T - times)) * e_payoff
    return {
        "mean_value": np.asarray(jnp.mean(values, axis=0)),
        "discounted_payoff": np.asarray(disc),
    }


@dataclasses.dataclass
class HedgeReport:
    """Bundled L6 outputs for one hedge run (what the notebooks print/plot)."""

    v0: float                      # learned t=0 price (adjusted units)
    phi0: float
    psi0: float
    discounted_payoff: float       # e^{-rT} E[payoff] comparison line
    var_by_date: np.ndarray        # (n_dates, n_q)
    var_overall: np.ndarray        # (n_q,)
    var_qs: tuple
    residual_stats: dict[str, float]
    fan: FanChart
    holdings: dict[str, np.ndarray]
    train_loss: np.ndarray
    train_mae: np.ndarray
    train_mape: np.ndarray
    epochs_ran: np.ndarray
    # unbiased QMC estimators (risk-neutral pipelines only; None otherwise):
    # v0_plain = e^{-rT} mean(payoff); v0_cv additionally subtracts the
    # learned-hedge martingale sum_t phi_t dM_t as a control variate — unbiased
    # regardless of hedge quality, unlike the network-predicted v0 (which
    # carries the reference's ~+8-13% regression-smoothing bias, Euro#20:
    # 11.352 vs ~10.39 Black-Scholes)
    v0_plain: float | None = None
    v0_cv: float | None = None
    cv_std: float | None = None  # per-path std of the CV estimator
    # v0_acv adds per-date OLS martingale controls on top of the learned
    # hedge (risk/controls.py) — the seed-robust price; acv_std its
    # per-path residual std
    v0_acv: float | None = None
    acv_std: float | None = None
    times: np.ndarray | None = None  # rebalance-knot times (n_dates+1,)
    oracle_mm: float | None = None  # moment-matched-lognormal basket oracle
    # (basket_hedge only; orp_tpu/utils/basket.py)

    def summary(self) -> str:
        qs = ", ".join(
            f"{q:.1%}: {v:,.4f}" for q, v in zip(self.var_qs, self.var_overall)
        )
        if self.discounted_payoff != 0.0:
            diff = f"diff {100 * (self.v0 / self.discounted_payoff - 1):+.3f}%"
        else:
            diff = "diff n/a (zero payoff)"
        cv = ""
        if self.v0_cv is not None:
            cv = (
                f"\nunbiased QMC price = {self.v0_plain:,.4f}, "
                f"hedged-CV price = {self.v0_cv:,.4f} (per-path std {self.cv_std:,.4f})"
            )
        if self.v0_acv is not None:
            cv += (
                f"\nOLS-martingale price = {self.v0_acv:,.4f} "
                f"(per-path std {self.acv_std:,.4f})"
            )
        return (
            f"V0 = {self.v0:,.4f} (discounted E[payoff] = {self.discounted_payoff:,.4f}, "
            f"{diff})\n"
            f"phi0 = {self.phi0:,.4f}, psi0 = {self.psi0:,.4f}\n"
            f"overall VaR  {qs}\n"
            f"residual P&L mean {self.residual_stats['mean']:+.4f} "
            f"std {self.residual_stats['std']:.4f}" + cv
        )


def build_report(
    result,
    *,
    terminal_payoff: jax.Array,
    r: float,
    times: jax.Array,
    adjustment_factor: float = 1.0,
    holdings_adjustment: float | None = None,
    var_qs=DEFAULT_VAR_QS,
    fan_qs=DEFAULT_FAN_QS,
    quantile_method: str = "sort",
) -> HedgeReport:
    """Assemble a full HedgeReport from a ``BackwardResult`` (orp_tpu.train.backward).

    ``adjustment_factor`` scales *values* (V0, discounted payoff);
    ``holdings_adjustment`` scales phi/psi — defaults to the same factor
    (pension semantics, RP.py:230: both x N0*P), but the European pipeline
    passes 1.0 because its phi is already a stock-value fraction (Euro#18).
    """
    if holdings_adjustment is None:
        holdings_adjustment = adjustment_factor
    holdings = holdings_summary(result.phi, result.psi, holdings_adjustment)
    T = float(np.asarray(times)[-1])
    adj = adjustment_factor
    disc = float(jnp.mean(terminal_payoff)) * float(np.exp(-r * T)) * adj
    # every value-denominated output scales by the same factor: the reference
    # multiplies the VaR/residual ledgers by ADJUSTMENT_FACTOR before reporting
    # (Multi#23 VaR in EUR; Euro#15-16 in units of S0)
    fan = fan_chart(result.values, fan_qs, method=quantile_method)
    fan = FanChart(qs=fan.qs, bands=fan.bands * adj, mean=fan.mean * adj)
    resid = residual_pnl_stats(result.var_residuals[:, -1])
    return HedgeReport(
        v0=float(jnp.mean(result.v0)) * adj,
        phi0=holdings["phi0"],
        psi0=holdings["psi0"],
        discounted_payoff=disc,
        var_by_date=var_by_date(result.var_residuals, var_qs, method=quantile_method) * adj,
        var_overall=var_overall(result.var_residuals, var_qs, method=quantile_method) * adj,
        var_qs=tuple(var_qs),
        residual_stats={k: v * adj for k, v in resid.items()},
        fan=fan,
        holdings=holdings,
        train_loss=result.train_loss,
        train_mae=result.train_mae,
        train_mape=result.train_mape,
        epochs_ran=result.epochs_ran,
        times=np.asarray(times),
    )


def to_frames(report: HedgeReport) -> dict:
    """Pandas-frame edge for notebook-style consumers (the shapes of
    ``Multi Time Step.ipynb#22-26``): VaR-by-date, holdings-by-date, fan-chart
    bands, and per-date training errors, all indexed by rebalance time.

    Pandas is imported here only — the analytics hot path stays array-native.
    """
    import pandas as pd

    times = report.times
    date_times = times[:-1] if times is not None else np.arange(len(report.train_loss))
    knot_times = times if times is not None else np.arange(report.fan.bands.shape[0])
    var = pd.DataFrame(
        report.var_by_date,
        index=pd.Index(date_times, name="time"),
        columns=[f"VaR_{q:g}" for q in report.var_qs],
    )
    holdings = pd.DataFrame(
        {
            "phi": report.holdings["phi_by_date"],
            "psi": report.holdings["psi_by_date"],
        },
        index=pd.Index(date_times, name="time"),
    )
    fan = pd.DataFrame(
        np.column_stack([report.fan.bands, report.fan.mean]),
        index=pd.Index(knot_times, name="time"),
        columns=[f"q{q:g}" for q in report.fan.qs] + ["mean"],
    )
    errors = pd.DataFrame(
        {
            "loss": report.train_loss,
            "mae": report.train_mae,
            "mape": report.train_mape,
            "epochs": report.epochs_ran,
        },
        index=pd.Index(date_times, name="time"),
    )
    return {"var": var, "holdings": holdings, "fan": fan, "errors": errors}
