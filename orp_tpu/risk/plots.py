"""Matplotlib reporting: the reference notebooks' chart set from a HedgeReport.

Parity targets (SURVEY.md §2 row 15):
- portfolio-value fan chart with quantile bands + discounted-payoff line
  (``European Options.ipynb#20``, ``Multi Time Step.ipynb#26``)
- phi/psi distributions over rebalance dates — violins (``Multi#25``, ``Euro#18``)
- residual P&L scatter vs terminal underlying (``Euro#15``)
- VaR-over-time curves with a zero line (``Multi#23``, ``Euro#16``)
- per-step training-error curve (``Multi#26``; the ``Errors`` ledger)

All functions take plain arrays / report objects, draw on a provided or fresh
Axes, and never require pandas/seaborn (violin via ``Axes.violinplot``).
Import of this module is optional — nothing else in the framework touches
matplotlib.
"""

from __future__ import annotations

import numpy as np


def _ax(ax):
    if ax is None:
        import matplotlib.pyplot as plt

        _, ax = plt.subplots(figsize=(10, 5))
    return ax


def fan_chart(report, times, *, ax=None, payoff_line: bool = True):
    """Quantile-band fan of portfolio value over time (Euro#20 shape)."""
    ax = _ax(ax)
    fan = report.fan
    t = np.asarray(times)
    n_q = fan.bands.shape[1]
    for i in range(n_q // 2):
        ax.fill_between(
            t, fan.bands[:, i], fan.bands[:, n_q - 1 - i],
            alpha=0.15, color="tab:blue", linewidth=0,
        )
    ax.plot(t, fan.mean, color="tab:blue", label="mean portfolio value")
    if payoff_line:
        ax.axhline(report.discounted_payoff, color="tab:orange", linestyle="--",
                   label="discounted E[payoff]")
    ax.set_xlabel("t (years)")
    ax.set_ylabel("V(t)")
    ax.legend()
    return ax


def holdings_violins(phi, psi, times, *, ax=None, max_dates: int = 20):
    """phi/psi per-date distributions as split violins (Multi#25 shape).

    ``phi``/``psi`` are ``(n_paths, n_dates)`` ledgers; ``times`` the date grid.
    Dates are subsampled to ``max_dates`` for readability.
    """
    ax = _ax(ax)
    phi = np.asarray(phi)
    psi = np.asarray(psi)
    t = np.asarray(times)[: phi.shape[1]]
    stride = max(1, phi.shape[1] // max_dates)
    sel = np.arange(0, phi.shape[1], stride)
    width = 0.8 * (t[stride] - t[0]) if len(t) > stride else 0.5
    for data, color, label in ((phi, "tab:blue", "phi"), (psi, "tab:orange", "psi")):
        parts = ax.violinplot(
            [data[:, i] for i in sel], positions=t[sel], widths=width,
            showmeans=True, showextrema=False,
        )
        for body in parts["bodies"]:
            body.set_facecolor(color)
            body.set_alpha(0.4)
        parts["cmeans"].set_color(color)
        ax.plot([], [], color=color, label=label)
    ax.set_xlabel("rebalance date (years)")
    ax.set_ylabel("holdings")
    ax.legend()
    return ax


def residual_scatter(residuals_T, underlying_T, *, ax=None):
    """Terminal hedge-residual P&L vs underlying (Euro#15 shape)."""
    ax = _ax(ax)
    ax.scatter(np.asarray(underlying_T), np.asarray(residuals_T), s=2, alpha=0.3)
    ax.axhline(0.0, color="k", linewidth=0.8)
    ax.set_xlabel("S(T)")
    ax.set_ylabel("replication residual at T")
    return ax


def var_over_time(report, times, *, ax=None):
    """Per-date VaR quantile curves with a zero line (Multi#23 shape)."""
    ax = _ax(ax)
    t = np.asarray(times)[: report.var_by_date.shape[0]]
    for j, q in enumerate(report.var_qs):
        ax.plot(t, report.var_by_date[:, j], label=f"VaR {q:.1%}")
    ax.axhline(0.0, color="k", linewidth=0.8)
    ax.set_xlabel("rebalance date (years)")
    ax.set_ylabel("residual quantile")
    ax.legend()
    return ax


def training_error_curve(report, times, *, ax=None):
    """Per-date fit MAE/MAPE (the Errors ledger plot, Multi#26 shape)."""
    ax = _ax(ax)
    t = np.asarray(times)[: len(report.train_mae)]
    ax.plot(t, report.train_mae, label="MAE")
    ax.set_xlabel("rebalance date (years)")
    ax.set_ylabel("MAE", color="tab:blue")
    ax2 = ax.twinx()
    ax2.plot(t, report.train_mape, color="tab:orange", label="MAPE %")
    ax2.set_ylabel("MAPE %", color="tab:orange")
    return ax
