"""Pathwise QMC greeks by forward-mode AD through the SDE engine.

The reference prices by eyeballing the learned V0 against a discounted mean
payoff (``European Options.ipynb#20``) and reads the hedge ratio off the
trained network; it has no sensitivities at all — NumPy ``for``-loop paths
cannot be differentiated. Here the simulation engine *is* a JAX program, so
first-order greeks come out of the same Sobol paths by automatic
differentiation, with no resimulation and no finite-difference bias:

- **delta, vega, rho** — pathwise (IPA) estimators: the a.s. derivative of the
  discounted payoff along each path, which is unbiased for Lipschitz payoffs
  (call/put). Computed with ``jax.jacfwd`` over a 4-parameter vector
  ``(s0, sigma, drift, tau)``; forward mode keeps memory at O(paths) through
  the whole ``lax.scan`` (reverse mode would checkpoint every step's state).
- **theta** — the same tangent pass through ``tau``, a time-dilation parameter
  multiplying every ``dt`` (maturity ``T_eff = tau * T``); calendar theta is
  ``-dV/dT = -(1/T) dV/dtau`` at ``tau = 1``.
- **gamma** — the pathwise second derivative of a kinked payoff is a.s. zero
  (the curvature lives entirely in the kink), so IPA cannot see it. Gamma is
  estimated by a common-random-numbers central difference of the *pathwise
  delta* (same Sobol indices, same scramble, spot bumped ±``gamma_bump``):
  the differenced indicator flips only for paths landing inside the bump
  window, so the estimator is a kernel-density read of the terminal density —
  O(h^2) bias, variance ~1/(n h), both tiny at QMC path counts.

Estimates ship with iid-formula standard errors as a *diagnostic only* — Sobol
points are not iid, so true QMC error is far smaller (use ``tools/rqmc_ci.py``
for honest confidence intervals).

Design notes (TPU-first): the path loop is the same ``scan_sde`` recurrence as
the pricing engine — Sobol dimensions stream per step, O(paths) memory at any
horizon — and the 4-wide tangent batch rides the same scan, so one fused XLA
program yields price + 4 sensitivities. Everything is elementwise over paths:
pass ``indices`` sharded over a ``("paths",)`` mesh and the whole computation
(including every tangent) shards with zero collectives until the final means.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import TypedDict

import jax
import jax.numpy as jnp

from orp_tpu.sde.grid import TimeGrid
from orp_tpu.sde.kernels import scan_sde


@dataclasses.dataclass(frozen=True)
class GreeksResult:
    """Point estimates + iid-diagnostic standard errors (see module docstring)."""

    price: float
    delta: float
    gamma: float
    vega: float
    rho: float
    theta: float
    se: dict[str, float]  # keys: price/delta/vega/rho/theta (gamma: FD of means)
    n_paths: int
    n_steps: int

    def as_dict(self) -> dict[str, float]:
        return {
            "price": self.price, "delta": self.delta, "gamma": self.gamma,
            "vega": self.vega, "rho": self.rho, "theta": self.theta,
        }


def _terminal_payoffs(params, indices, grid, k, is_call, seed, scramble, dtype):
    """Per-path discounted payoff as a differentiable function of
    ``params = (s0, sigma, drift, tau)``.

    Log-RETURN accumulation with ``s0`` applied as an output scale — the same
    no-device-log policy as ``simulate_gbm_log`` (SCALING.md §6d) — so the
    primal here is the pricing engine's arithmetic, not a lookalike.
    """
    s0, sigma, drift, tau = params
    dt_eff = tau * grid.dt
    sdt_eff = jnp.sqrt(dt_eff)
    c0 = (drift - 0.5 * sigma * sigma) * dt_eff

    def step(acc, z, t, dt):
        return acc + c0 + sigma * sdt_eff * z[:, 0]

    state0 = jnp.zeros(indices.shape, dtype)
    acc, _ = scan_sde(
        step, state0, lambda x: x, indices, grid, 1, seed,
        scramble=scramble, store_every=grid.n_steps, dtype=dtype,
    )
    s_t = s0 * jnp.exp(acc)
    payoff = jnp.maximum(s_t - k, 0.0) if is_call else jnp.maximum(k - s_t, 0.0)
    horizon = jnp.asarray(grid.T, dtype) * tau
    return jnp.exp(-drift * horizon) * payoff


@functools.partial(
    jax.jit, static_argnames=("grid", "is_call", "seed", "scramble", "dtype")
)
def _pathwise_jacobian(params, indices, grid, k, is_call, seed, scramble, dtype):
    """(per-path discounted payoffs (n,), per-path jacobian (n, 4)) in ONE scan:
    the 4 unit tangents ride the primal recurrence as a forward-mode batch."""
    fn = functools.partial(
        _terminal_payoffs, indices=indices, grid=grid, k=k, is_call=is_call,
        seed=seed, scramble=scramble, dtype=dtype,
    )
    # vmap(jvp) with a shared primal (out_axes=(None, 0)): ONE compiled scan
    # carries primal + all 4 tangents (fn(params) + jacfwd(fn)(params) would
    # compile a second, discarded primal sweep — verified in optimized HLO)
    v, jac_t = jax.vmap(
        lambda t: jax.jvp(fn, (params,), (t,)), out_axes=(None, 0)
    )(jnp.eye(4, dtype=params.dtype))
    return v, jac_t.T  # (n,), (n, 4)


@functools.partial(
    jax.jit, static_argnames=("grid", "is_call", "seed", "scramble", "dtype")
)
def _pathwise_delta(params, indices, grid, k, is_call, seed, scramble, dtype):
    """Mean pathwise delta only — a single s0 tangent (for the gamma bumps,
    which don't need the other three tangent scans)."""
    fn = functools.partial(
        _terminal_payoffs, indices=indices, grid=grid, k=k, is_call=is_call,
        seed=seed, scramble=scramble, dtype=dtype,
    )
    tangent = jnp.zeros_like(params).at[0].set(1.0)
    _, dv = jax.jvp(fn, (params,), (tangent,))
    return jnp.mean(dv)


def european_greeks(
    n_paths: int,
    s0: float,
    k: float,
    r: float,
    sigma: float,
    T: float,
    *,
    kind: str = "call",
    n_steps: int = 52,
    seed: int = 1234,
    scramble: str = "owen",
    gamma_bump: float = 0.01,
    indices: jax.Array | None = None,
    dtype=jnp.float32,
) -> GreeksResult:
    """Price + (delta, gamma, vega, rho, theta) of a European option from one
    Sobol path set, by pathwise AD through the log-Euler engine.

    ``gamma_bump`` is the relative spot bump of the CRN delta difference
    (default 1% of ``s0``). ``indices`` overrides the Sobol index range (pass a
    path-sharded array to run the whole computation under a mesh).
    """
    if kind not in ("call", "put"):
        raise ValueError(f"kind must be 'call' or 'put', got {kind!r}")
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    grid = TimeGrid(T, n_steps)
    params = jnp.asarray([s0, sigma, r, 1.0], dtype)
    is_call = kind == "call"

    v, jac = _pathwise_jacobian(
        params, indices, grid, k, is_call, seed, scramble, dtype
    )

    price, se_price = _mean_se(v)
    delta, se_delta = _mean_se(jac[:, 0])
    vega, se_vega = _mean_se(jac[:, 1])
    rho, se_rho = _mean_se(jac[:, 2])
    dv_dtau, se_tau = _mean_se(jac[:, 3])
    theta = -dv_dtau / T  # dV/dt = -(1/T) dV/dtau at tau=1

    # CRN central difference of the PATHWISE delta column (not of prices):
    # same indices, same scramble -> only kink-window paths contribute
    h = gamma_bump * s0
    dsum = jnp.zeros((), dtype)
    for sgn in (1.0, -1.0):
        pb = params.at[0].add(sgn * h)
        dsum = dsum + sgn * _pathwise_delta(
            pb, indices, grid, k, is_call, seed, scramble, dtype
        )
    gamma = float(dsum) / (2.0 * h)

    return GreeksResult(
        price=price, delta=delta, gamma=gamma, vega=vega, rho=rho, theta=theta,
        se={
            "price": se_price, "delta": se_delta, "vega": se_vega,
            "rho": se_rho, "theta": se_tau / T,
        },
        n_paths=v.shape[0], n_steps=n_steps,
    )


# ---------------------------------------------------------------------------
# Digital options: likelihood-ratio sensitivities (where pathwise AD fails)
# ---------------------------------------------------------------------------


def digital_greeks(
    n_paths: int,
    s0: float,
    k: float,
    r: float,
    sigma: float,
    T: float,
    *,
    kind: str = "call",
    n_steps: int = 52,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jax.Array | None = None,
    dtype=jnp.float32,
) -> dict[str, object]:
    """Cash-or-nothing digital: price + LIKELIHOOD-RATIO delta/vega.

    The counterpoint to the pathwise estimators above: a digital payoff is
    an indicator, so the pathwise derivative is a.s. ZERO — IPA is silently
    wrong, not merely noisy. The likelihood-ratio method differentiates the
    DENSITY instead: for terminal GBM with ``z = (log(S_T/s0) - (r -
    sigma^2/2)T) / (sigma sqrt(T))``,

        delta = e^{-rT} E[1_payoff * z / (s0 sigma sqrt(T))]
        vega  = e^{-rT} E[1_payoff * ((z^2 - 1)/sigma - z sqrt(T))]

    which needs no payoff smoothness at all. Oracles: the closed forms
    ``e^{-rT} phi(d2)/(s0 sigma sqrt(T))`` and ``-e^{-rT} phi(d2) d1 /
    sigma`` (``tests/test_greeks.py``). ``z`` comes straight from the
    scan's accumulated log-return — no device log anywhere (the §6d
    policy), and no density evaluation on device."""
    if kind not in ("call", "put"):
        raise ValueError(f"kind must be 'call' or 'put', got {kind!r}")
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    grid = TimeGrid(T, n_steps)
    sq = sigma * math.sqrt(T)
    acc_drift = (r - 0.5 * sigma * sigma) * T

    # the engine's log-return recurrence directly: the accumulator IS the
    # log-return, so z needs no device log (re-logging s0*exp(acc) would
    # re-introduce exactly the ulp class SCALING.md §6d eliminated)
    sdt = jnp.asarray(grid.dt, dtype) ** 0.5
    c0 = (r - 0.5 * sigma * sigma) * grid.dt

    def step(acc, zz, t, dt):
        return acc + c0 + sigma * sdt * zz[:, 0]

    acc, _ = scan_sde(
        step, jnp.zeros(indices.shape, dtype), lambda a: a, indices, grid,
        1, seed, scramble=scramble, store_every=n_steps, dtype=dtype,
    )
    z = (acc - acc_drift) / sq
    s_t = jnp.asarray(s0, dtype) * jnp.exp(acc)
    sign = 1.0 if kind == "call" else -1.0
    hit = (sign * (s_t - k) > 0.0).astype(dtype)
    disc = jnp.exp(jnp.asarray(-r * T, dtype))
    price, se_price = _mean_se(disc * hit)
    delta, se_delta = _mean_se(disc * hit * z / (s0 * sq))
    vega, se_vega = _mean_se(disc * hit * ((z * z - 1.0) / sigma
                                           - z * math.sqrt(T)))
    return {
        "price": price, "delta": delta, "vega": vega,
        "se": {"price": se_price, "delta": se_delta, "vega": se_vega},
        "n_paths": int(hit.shape[0]), "n_steps": n_steps,
    }


# ---------------------------------------------------------------------------
# Heston: pathwise sensitivities through the full-truncation-Euler scan
# ---------------------------------------------------------------------------


def _mean_se(x) -> tuple[float, float]:
    """(mean, iid-diagnostic standard error) of a per-path column."""
    n = x.shape[0]
    return float(jnp.mean(x)), float(jnp.std(x) / jnp.sqrt(n))


def _safe_sqrt(x):
    """sqrt with subgradient 0 at the truncation boundary: full-truncation
    Euler clamps v at 0, where ``d sqrt/dv = inf`` would poison every tangent
    of a path that ever touches the floor. The double-``where`` keeps the
    primal exact and the tangent finite (0) on the clamped set."""
    pos = x > 0.0
    return jnp.where(pos, jnp.sqrt(jnp.where(pos, x, 1.0)), 0.0)


def _heston_payoffs(params, indices, grid, k, rho, is_call, seed, scramble, dtype):
    """Per-path discounted payoff as a differentiable function of
    ``params = (s0, v0, kappa, theta, xi, r)`` — the same full-truncation
    recurrence as ``simulate_heston_log`` (kernels.py:406), log-return
    accumulated, with the correlation ``rho`` held static."""
    s0, v0, kappa, theta, xi, r = params
    sdt = jnp.sqrt(jnp.asarray(grid.dt, dtype))
    rho_c = (1.0 - rho * rho) ** 0.5

    def step(state, z, t, dt):
        logs, v = state
        vp = jnp.maximum(v, 0.0)
        sv = _safe_sqrt(vp)
        zs = rho * z[:, 1] + rho_c * z[:, 0]
        logs = logs + (r - 0.5 * vp) * dt + sv * sdt * zs
        v = v + kappa * (theta - vp) * dt + xi * sv * sdt * z[:, 1]
        return (logs, v)

    n = indices.shape[0]
    state0 = (jnp.zeros((n,), dtype), jnp.full((n,), v0, dtype))
    (acc, _), _ = scan_sde(
        step, state0, lambda s: s[0], indices, grid, 2, seed,
        scramble=scramble, store_every=grid.n_steps, dtype=dtype,
    )
    s_t = s0 * jnp.exp(acc)
    payoff = jnp.maximum(s_t - k, 0.0) if is_call else jnp.maximum(k - s_t, 0.0)
    return jnp.exp(-r * grid.T) * payoff


@functools.partial(
    jax.jit,
    static_argnames=("grid", "rho", "is_call", "seed", "scramble", "dtype"),
)
def _heston_jacobian(params, indices, grid, k, rho, is_call, seed, scramble, dtype):
    fn = functools.partial(
        _heston_payoffs, indices=indices, grid=grid, k=k, rho=rho,
        is_call=is_call, seed=seed, scramble=scramble, dtype=dtype,
    )
    # shared-primal tangent batch: one scan, not fn + jacfwd's second sweep
    v, jac_t = jax.vmap(
        lambda t: jax.jvp(fn, (params,), (t,)), out_axes=(None, 0)
    )(jnp.eye(6, dtype=params.dtype))
    return v, jac_t.T  # (n,), (n, 6)


class HestonGreeks(TypedDict):
    price: float
    delta: float
    vega_v0: float
    vega_kappa: float
    vega_theta: float
    vega_xi: float
    rho_rate: float
    se: dict[str, float]
    n_paths: int
    n_steps: int


def heston_greeks(
    n_paths: int,
    s0: float,
    k: float,
    r: float,
    T: float,
    *,
    v0: float,
    kappa: float,
    theta: float,
    xi: float,
    rho: float,
    kind: str = "call",
    n_steps: int = 364,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jax.Array | None = None,
    dtype=jnp.float32,
) -> HestonGreeks:
    """Price + pathwise sensitivities of a European option under Heston, by
    forward-mode AD through the full-truncation-Euler scan: ``delta`` (spot),
    ``vega_v0``/``vega_theta``/``vega_kappa``/``vega_xi`` (the four variance-
    dynamics sensitivities — no closed form exists for any of them) and
    ``rho_rate``. The correlation ``rho`` stays a static config (its pathwise
    derivative needs the z-rotation tangent; bump-reprice it with CRN if
    needed). Returns a flat dict with an ``se`` sub-dict (iid-diagnostic)."""
    if kind not in ("call", "put"):
        raise ValueError(f"kind must be 'call' or 'put', got {kind!r}")
    if not -1.0 <= rho <= 1.0:
        # (1 - rho^2)**0.5 on a Python float silently goes COMPLEX past +/-1
        # and would poison the whole simulation far from the bad input
        raise ValueError(f"rho must be in [-1, 1], got {rho!r}")
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    grid = TimeGrid(T, n_steps)
    params = jnp.asarray([s0, v0, kappa, theta, xi, r], dtype)

    v, jac = _heston_jacobian(
        params, indices, grid, k, float(rho), kind == "call", seed, scramble,
        dtype,
    )
    names = ("price", "delta", "vega_v0", "vega_kappa", "vega_theta",
             "vega_xi", "rho_rate")
    cols = (v, jac[:, 0], jac[:, 1], jac[:, 2], jac[:, 3], jac[:, 4],
            jac[:, 5])
    stats = {name: _mean_se(col) for name, col in zip(names, cols)}
    out = {name: m for name, (m, _) in stats.items()}
    out["se"] = {name: s for name, (_, s) in stats.items()}
    out["n_paths"] = v.shape[0]
    out["n_steps"] = n_steps
    return out  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# Basket: per-asset delta/vega vectors through the correlated scan
# ---------------------------------------------------------------------------


def _basket_payoffs(s0, sigma, r, indices, grid, weights, k, corr_chol,
                    seed, scramble, dtype):
    """Per-path discounted basket-call payoff, differentiable in the
    per-asset ``s0``/``sigma`` vectors and the rate ``r`` — the same
    correlated log-return recurrence as ``simulate_gbm_basket``
    (kernels.py:461), Cholesky factor held static."""
    n_assets = weights.shape[0]
    sdt = jnp.sqrt(jnp.asarray(grid.dt, dtype))
    c0 = (r - 0.5 * sigma * sigma) * grid.dt  # (A,)

    def step(logs, z, t, dt):
        zc = jnp.matmul(z, corr_chol.T, precision="highest")
        return logs + c0[None, :] + sigma[None, :] * sdt * zc

    state0 = jnp.zeros((indices.shape[0], n_assets), dtype)
    acc, _ = scan_sde(
        step, state0, lambda x: x, indices, grid, n_assets, seed,
        scramble=scramble, store_every=grid.n_steps, dtype=dtype,
    )
    s_t = s0[None, :] * jnp.exp(acc)  # (n, A)
    basket = s_t @ weights
    return jnp.exp(-r * grid.T) * jnp.maximum(basket - k, 0.0)


@functools.partial(
    jax.jit, static_argnames=("grid", "seed", "scramble", "dtype")
)
def _basket_jacobian(s0, sigma, r, indices, grid, weights, k, corr_chol,
                     seed, scramble, dtype):
    fn = functools.partial(
        _basket_payoffs, indices=indices, grid=grid, weights=weights, k=k,
        corr_chol=corr_chol, seed=seed, scramble=scramble, dtype=dtype,
    )
    # all 2A+1 tangents (per-asset s0, per-asset sigma, rate) share ONE
    # primal scan via vmap(jvp) — fn + two jacfwd + a jvp would sweep the
    # primal four times
    n_assets = s0.shape[0]
    zero_a = jnp.zeros((n_assets, n_assets), dtype)
    eye_a = jnp.eye(n_assets, dtype=dtype)
    t_s0 = jnp.concatenate([eye_a, zero_a, jnp.zeros((1, n_assets), dtype)])
    t_sig = jnp.concatenate([zero_a, eye_a, jnp.zeros((1, n_assets), dtype)])
    t_r = jnp.concatenate([jnp.zeros((2 * n_assets,), dtype),
                           jnp.ones((1,), dtype)])
    v, tang = jax.vmap(
        lambda a, b, c: jax.jvp(fn, (s0, sigma, r), (a, b, c)),
        out_axes=(None, 0),
    )(t_s0, t_sig, t_r)  # tang: (2A+1, n)
    return (v, tang[:n_assets].T, tang[n_assets:2 * n_assets].T,
            tang[2 * n_assets])


def basket_greeks(
    n_paths: int,
    *,
    s0,
    weights,
    strike: float,
    r: float,
    sigma,
    corr,
    T: float,
    n_steps: int = 52,
    seed: int = 1234,
    scramble: str = "owen",
    indices: jax.Array | None = None,
    dtype=jnp.float32,
) -> dict[str, object]:
    """Price + per-asset delta and vega vectors (and rate rho) of a
    basket call ``max(sum_i w_i S_T^i - K, 0)``, by pathwise AD through the
    correlated log-Euler scan. Returns arrays for ``delta``/``vega``
    (shape (A,)) and floats for ``price``/``rho_rate``; the only oracle with
    a closed form is the degenerate identical-asset case (= Black-Scholes,
    pinned in tests) — the general case is validated against CRN
    bump-reprice differences."""
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    grid = TimeGrid(T, n_steps)
    s0 = jnp.asarray(s0, dtype)
    sigma = jnp.asarray(sigma, dtype)
    weights = jnp.asarray(weights, dtype)
    chol = jnp.linalg.cholesky(jnp.asarray(corr, dtype))
    r_ = jnp.asarray(r, dtype)

    v, d_s0, d_sig, d_r = _basket_jacobian(
        s0, sigma, r_, indices, grid, weights, strike, chol, seed, scramble,
        dtype,
    )
    price, se_price = _mean_se(v)
    return {
        "price": price,
        "delta": jnp.mean(d_s0, axis=0),   # (A,)
        "vega": jnp.mean(d_sig, axis=0),   # (A,)
        "rho_rate": float(jnp.mean(d_r)),
        "se": {"price": se_price},
        "n_paths": v.shape[0],
        "n_steps": n_steps,
    }
