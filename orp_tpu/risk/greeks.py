"""Pathwise QMC greeks by forward-mode AD through the SDE engine.

The reference prices by eyeballing the learned V0 against a discounted mean
payoff (``European Options.ipynb#20``) and reads the hedge ratio off the
trained network; it has no sensitivities at all — NumPy ``for``-loop paths
cannot be differentiated. Here the simulation engine *is* a JAX program, so
first-order greeks come out of the same Sobol paths by automatic
differentiation, with no resimulation and no finite-difference bias:

- **delta, vega, rho** — pathwise (IPA) estimators: the a.s. derivative of the
  discounted payoff along each path, which is unbiased for Lipschitz payoffs
  (call/put). Computed with ``jax.jacfwd`` over a 4-parameter vector
  ``(s0, sigma, drift, tau)``; forward mode keeps memory at O(paths) through
  the whole ``lax.scan`` (reverse mode would checkpoint every step's state).
- **theta** — the same tangent pass through ``tau``, a time-dilation parameter
  multiplying every ``dt`` (maturity ``T_eff = tau * T``); calendar theta is
  ``-dV/dT = -(1/T) dV/dtau`` at ``tau = 1``.
- **gamma** — the pathwise second derivative of a kinked payoff is a.s. zero
  (the curvature lives entirely in the kink), so IPA cannot see it. Gamma is
  estimated by a common-random-numbers central difference of the *pathwise
  delta* (same Sobol indices, same scramble, spot bumped ±``gamma_bump``):
  the differenced indicator flips only for paths landing inside the bump
  window, so the estimator is a kernel-density read of the terminal density —
  O(h^2) bias, variance ~1/(n h), both tiny at QMC path counts.

Estimates ship with iid-formula standard errors as a *diagnostic only* — Sobol
points are not iid, so true QMC error is far smaller (use ``tools/rqmc_ci.py``
for honest confidence intervals).

Design notes (TPU-first): the path loop is the same ``scan_sde`` recurrence as
the pricing engine — Sobol dimensions stream per step, O(paths) memory at any
horizon — and the 4-wide tangent batch rides the same scan, so one fused XLA
program yields price + 4 sensitivities. Everything is elementwise over paths:
pass ``indices`` sharded over a ``("paths",)`` mesh and the whole computation
(including every tangent) shards with zero collectives until the final means.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from orp_tpu.sde.grid import TimeGrid
from orp_tpu.sde.kernels import scan_sde


@dataclasses.dataclass(frozen=True)
class GreeksResult:
    """Point estimates + iid-diagnostic standard errors (see module docstring)."""

    price: float
    delta: float
    gamma: float
    vega: float
    rho: float
    theta: float
    se: dict[str, float]  # keys: price/delta/vega/rho/theta (gamma: FD of means)
    n_paths: int
    n_steps: int

    def as_dict(self) -> dict[str, float]:
        return {
            "price": self.price, "delta": self.delta, "gamma": self.gamma,
            "vega": self.vega, "rho": self.rho, "theta": self.theta,
        }


def _terminal_payoffs(params, indices, grid, k, is_call, seed, scramble, dtype):
    """Per-path discounted payoff as a differentiable function of
    ``params = (s0, sigma, drift, tau)``.

    Log-RETURN accumulation with ``s0`` applied as an output scale — the same
    no-device-log policy as ``simulate_gbm_log`` (SCALING.md §6d) — so the
    primal here is the pricing engine's arithmetic, not a lookalike.
    """
    s0, sigma, drift, tau = params
    dt_eff = tau * grid.dt
    sdt_eff = jnp.sqrt(dt_eff)
    c0 = (drift - 0.5 * sigma * sigma) * dt_eff

    def step(acc, z, t, dt):
        return acc + c0 + sigma * sdt_eff * z[:, 0]

    state0 = jnp.zeros(indices.shape, dtype)
    acc, _ = scan_sde(
        step, state0, lambda x: x, indices, grid, 1, seed,
        scramble=scramble, store_every=grid.n_steps, dtype=dtype,
    )
    s_t = s0 * jnp.exp(acc)
    payoff = jnp.maximum(s_t - k, 0.0) if is_call else jnp.maximum(k - s_t, 0.0)
    horizon = jnp.asarray(grid.T, dtype) * tau
    return jnp.exp(-drift * horizon) * payoff


@functools.partial(
    jax.jit, static_argnames=("grid", "is_call", "seed", "scramble", "dtype")
)
def _pathwise_jacobian(params, indices, grid, k, is_call, seed, scramble, dtype):
    """(per-path discounted payoffs (n,), per-path jacobian (n, 4)) in ONE scan:
    the 4 unit tangents ride the primal recurrence as a forward-mode batch."""
    fn = functools.partial(
        _terminal_payoffs, indices=indices, grid=grid, k=k, is_call=is_call,
        seed=seed, scramble=scramble, dtype=dtype,
    )
    v = fn(params)
    jac = jax.jacfwd(fn)(params)  # (n, 4)
    return v, jac


@functools.partial(
    jax.jit, static_argnames=("grid", "is_call", "seed", "scramble", "dtype")
)
def _pathwise_delta(params, indices, grid, k, is_call, seed, scramble, dtype):
    """Mean pathwise delta only — a single s0 tangent (for the gamma bumps,
    which don't need the other three tangent scans)."""
    fn = functools.partial(
        _terminal_payoffs, indices=indices, grid=grid, k=k, is_call=is_call,
        seed=seed, scramble=scramble, dtype=dtype,
    )
    tangent = jnp.zeros_like(params).at[0].set(1.0)
    _, dv = jax.jvp(fn, (params,), (tangent,))
    return jnp.mean(dv)


def european_greeks(
    n_paths: int,
    s0: float,
    k: float,
    r: float,
    sigma: float,
    T: float,
    *,
    kind: str = "call",
    n_steps: int = 52,
    seed: int = 1234,
    scramble: str = "owen",
    gamma_bump: float = 0.01,
    indices: jax.Array | None = None,
    dtype=jnp.float32,
) -> GreeksResult:
    """Price + (delta, gamma, vega, rho, theta) of a European option from one
    Sobol path set, by pathwise AD through the log-Euler engine.

    ``gamma_bump`` is the relative spot bump of the CRN delta difference
    (default 1% of ``s0``). ``indices`` overrides the Sobol index range (pass a
    path-sharded array to run the whole computation under a mesh).
    """
    if kind not in ("call", "put"):
        raise ValueError(f"kind must be 'call' or 'put', got {kind!r}")
    if indices is None:
        indices = jnp.arange(n_paths, dtype=jnp.uint32)
    grid = TimeGrid(T, n_steps)
    params = jnp.asarray([s0, sigma, r, 1.0], dtype)
    is_call = kind == "call"

    v, jac = _pathwise_jacobian(
        params, indices, grid, k, is_call, seed, scramble, dtype
    )
    n = v.shape[0]

    def mean_se(x):
        m = jnp.mean(x)
        return float(m), float(jnp.std(x) / jnp.sqrt(n))

    price, se_price = mean_se(v)
    delta, se_delta = mean_se(jac[:, 0])
    vega, se_vega = mean_se(jac[:, 1])
    rho, se_rho = mean_se(jac[:, 2])
    dv_dtau, se_tau = mean_se(jac[:, 3])
    theta = -dv_dtau / T  # dV/dt = -(1/T) dV/dtau at tau=1

    # CRN central difference of the PATHWISE delta column (not of prices):
    # same indices, same scramble -> only kink-window paths contribute
    h = gamma_bump * s0
    dsum = jnp.zeros((), dtype)
    for sgn in (1.0, -1.0):
        pb = params.at[0].add(sgn * h)
        dsum = dsum + sgn * _pathwise_delta(
            pb, indices, grid, k, is_call, seed, scramble, dtype
        )
    gamma = float(dsum) / (2.0 * h)

    return GreeksResult(
        price=price, delta=delta, gamma=gamma, vega=vega, rho=rho, theta=theta,
        se={
            "price": se_price, "delta": se_delta, "vega": se_vega,
            "rho": se_rho, "theta": se_tau / T,
        },
        n_paths=n, n_steps=n_steps,
    )
