"""Side pipeline: CIR volatility-parameter calibration (SURVEY.md §2 row 16)."""

from orp_tpu.calib.cir import (
    CIRParams,
    annualized_drift,
    estimate_cir_params,
    log_returns,
    rolling_volatility,
)

__all__ = [
    "CIRParams",
    "annualized_drift",
    "estimate_cir_params",
    "log_returns",
    "rolling_volatility",
]
