"""Side pipeline: CIR volatility-parameter calibration (SURVEY.md §2 row 16)."""

from orp_tpu.calib.cir import (
    CalibrationFit,
    CIRParams,
    annualized_drift,
    calibrate_prices,
    estimate_cir_params,
    log_returns,
    rolling_volatility,
)

__all__ = [
    "CalibrationFit",
    "CIRParams",
    "annualized_drift",
    "calibrate_prices",
    "estimate_cir_params",
    "log_returns",
    "rolling_volatility",
]
