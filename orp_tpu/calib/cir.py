"""CIR vol-parameter calibration from a price history (closed-form OLS).

Re-design of ``Extra: Stochastic Volatility.ipynb``:

- ``CIRParams`` dataclass with the Feller-type ``2ab >= c^2`` validation (#3 —
  the single input validation in the whole reference);
- ``estimate_cir_params`` (#4): OLS of ``dsigma/sqrt(sigma)`` on
  ``[1/sqrt(sigma), sqrt(sigma)]`` without intercept — solved in closed form
  by ``np.linalg.lstsq`` instead of sklearn's LinearRegression. Calibration is
  a host-side pipeline (tiny data, float64) so it runs in NumPy, keeping the
  device path free of it;
- ``rolling_volatility`` (#7): 40-day rolling std of log returns x sqrt(252);
- ``annualized_drift`` (#7): ``mu = log(P_T / P_0) / years``.

Market-data *ingestion* stays host-side and offline (the reference pulls ^GSPC
via yfinance — a network boundary this framework deliberately keeps outside the
compute path): callers pass a price/return array from any source. The
calibrated constants feed ``orp_tpu.api.StochVolConfig`` directly instead of
being hand-pasted into notebook cells (the reference copies ``#8(out)`` into
``Multi Time Step.ipynb#9/#32`` manually).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class CIRParams:
    """CIR process parameters; requires the Feller-type condition 2ab >= c^2
    (``Extra: Stochastic Volatility.ipynb#3`` — whose error message states the
    inequality backwards; the *check* is reproduced, the message corrected)."""

    a: float  # mean-reversion speed
    b: float  # asymptotic mean
    c: float  # Brownian scale (vol-of-vol)

    def __post_init__(self):
        if 2 * self.a * self.b < self.c**2:
            raise ValueError(
                f"Feller condition violated: 2ab = {2 * self.a * self.b:.3e} "
                f"< c^2 = {self.c**2:.3e}"
            )


def log_returns(prices) -> np.ndarray:
    """Daily log returns ``log(P_t / P_{t-1})`` (#5)."""
    p = np.asarray(prices, np.float64)
    return np.log(p[1:] / p[:-1])


def rolling_volatility(
    returns, window: int = 40, annualization: float = 252.0
) -> np.ndarray:
    """Rolling-window std of returns x sqrt(annualization) (#7, ``HV40D``).

    Sample std (ddof=1, pandas ``rolling().std()`` semantics). Computed with
    cumulative sums — O(n), no Python loop.
    """
    r = np.asarray(returns, np.float64)
    n = r.shape[0]
    if n < window:
        raise ValueError(f"need >= {window} returns, got {n}")
    c1 = np.concatenate([np.zeros(1), np.cumsum(r)])
    c2 = np.concatenate([np.zeros(1), np.cumsum(r * r)])
    s1 = c1[window:] - c1[:-window]
    s2 = c2[window:] - c2[:-window]
    var = (s2 - s1 * s1 / window) / (window - 1)
    return np.sqrt(np.maximum(var, 0.0) * annualization)


def annualized_drift(prices, years: float) -> float:
    """``mu = log(P_end / P_0) / years`` (#7)."""
    p = np.asarray(prices)
    return float(np.log(p[-1] / p[0]) / years)


def estimate_cir_params(sigma_t) -> CIRParams:
    """OLS CIR estimate from a vol series (#4 semantics, lstsq closed form).

    Regression: ``dsigma_t / sqrt(sigma_t) = ab * (1/sqrt(sigma_t))
    - a * sqrt(sigma_t) + eps``; ``c`` is the residual std (population std,
    matching the notebook's ``np.std``).
    """
    s = np.asarray(sigma_t, np.float64)
    if s.shape[0] < 3:
        raise ValueError("need at least 3 observations")
    if (s <= 0).any():
        raise ValueError("vol series must be strictly positive")
    sqrt_s = np.sqrt(s[:-1])
    y = np.diff(s) / sqrt_s
    X = np.stack([1.0 / sqrt_s, sqrt_s], axis=-1)
    coef, _, _, _ = np.linalg.lstsq(X, y, rcond=None)
    ab, neg_a = float(coef[0]), float(coef[1])
    a = -neg_a
    if a <= 1e-12:
        # a trending/non-mean-reverting series: the OLS speed is <= 0 and
        # b = ab/a would be negative or blow up — refuse rather than return an
        # explosive CIR parameterisation
        raise ValueError(
            f"series shows no mean reversion (estimated speed a = {a:.3e} <= 0); "
            "CIR calibration is not applicable"
        )
    b = ab / a
    resid = y - X @ coef
    c = float(np.std(resid))
    return CIRParams(a=a, b=b, c=c)  # __post_init__ enforces Feller 2ab >= c^2


@dataclasses.dataclass(frozen=True)
class CalibrationFit:
    """One complete calibration from a raw price series: the CIR vol
    parameters plus the drift and current-vol state the hedging pipelines
    consume (``StochVolConfig(a, b, c, v0)`` / ``EuropeanConfig(sigma=...)``).
    """

    params: CIRParams
    mu: float        # annualized drift over the series
    sigma0: float    # last rolling-window vol — the current vol state
    n_prices: int
    vol_window: int

    def as_dict(self) -> dict:
        return {"a": self.params.a, "b": self.params.b, "c": self.params.c,
                "mu": self.mu, "sigma0": self.sigma0,
                "n_prices": self.n_prices, "vol_window": self.vol_window}


def calibrate_prices(prices, *, vol_window: int = 40, years: float | None = None,
                     annualization: float = 252.0) -> CalibrationFit:
    """The one-call calibration the CLI and the pilot loop drive: prices ->
    log returns -> rolling vol -> OLS CIR params + drift + current vol.

    ``years`` defaults to ``n_returns / annualization`` (daily prices);
    pass it explicitly for non-daily sampling. Needs at least
    ``vol_window + 3`` prices (``vol_window + 2`` returns give the 3 vol
    observations the OLS requires)."""
    p = np.asarray(prices, np.float64)
    if p.ndim != 1:
        raise ValueError(f"prices must be 1-D, got shape {p.shape}")
    if p.shape[0] < vol_window + 3:
        raise ValueError(
            f"need >= {vol_window + 3} prices for vol_window={vol_window} "
            f"(got {p.shape[0]}): the rolling vol needs vol_window + 2 "
            "returns to yield the 3 observations the CIR OLS requires")
    if (p <= 0).any():
        raise ValueError("prices must be strictly positive")
    r = log_returns(p)
    sigma = rolling_volatility(r, window=vol_window,
                               annualization=annualization)
    if years is None:
        years = r.shape[0] / annualization
    return CalibrationFit(
        params=estimate_cir_params(sigma),
        mu=annualized_drift(p, years),
        sigma0=float(sigma[-1]),
        n_prices=int(p.shape[0]),
        vol_window=int(vol_window),
    )
