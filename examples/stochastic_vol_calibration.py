"""CIR vol calibration + sanity simulation — parity example for
``Extra: Stochastic Volatility.ipynb``.

The reference downloads 10y of ^GSPC via yfinance (``Extra: Stochastic
Volatility.ipynb#5``) — a network dependency this framework keeps out of the
compute path. Three input modes, most-reproducible first:

- ``prices.csv``       — any price CSV (one close per line);
- ``--ticker ^GSPC``   — the reference's live pull, used ONLY if yfinance is
  importable (an optional extra, never a framework dependency) and the
  network is reachable; degrades with a clear message otherwise;
- no argument          — a synthetic GBM series (fully offline/reproducible).

Reference output to compare (Extra#8(out)): CIRParams(a=0.00336, b=0.15431,
c=0.01583).

Run: env -u PALLAS_AXON_POOL_IPS python examples/stochastic_vol_calibration.py [prices.csv | --ticker ^GSPC]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


import jax.numpy as jnp
import numpy as np

from orp_tpu.calib import annualized_drift, estimate_cir_params, log_returns, rolling_volatility
from orp_tpu.sde import TimeGrid, simulate_pension


def _fetch_ticker(symbol: str, years: float) -> np.ndarray:
    """The reference's yfinance pull (Extra#5: ``yf.download('^GSPC',
    period='10y')['Close']``), behind an import guard — yfinance is an
    optional extra, not a framework dependency."""
    try:
        import yfinance as yf
    except ImportError:
        raise SystemExit(
            "--ticker needs the optional yfinance package (pip install "
            "yfinance); alternatively pass a price CSV — the calibration "
            "itself is offline"
        )
    data = yf.download(symbol, period=f"{int(years)}y", progress=False)
    if data is None or getattr(data, "empty", True) or "Close" not in data:
        raise SystemExit(
            f"--ticker {symbol}: empty download — network/symbol problem? "
            "Pass a price CSV instead"
        )
    closes = np.asarray(data["Close"], dtype=float).ravel()
    closes = closes[np.isfinite(closes)]  # partial downloads carry NaN rows
    if closes.size < 100:
        raise SystemExit(
            f"--ticker {symbol}: got {closes.size} usable closes — network/"
            "symbol problem? Pass a price CSV instead"
        )
    return closes


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--ticker":
        if len(sys.argv) < 3:
            raise SystemExit("usage: ... --ticker SYMBOL  (e.g. --ticker ^GSPC)")
        years = 10.0
        prices = _fetch_ticker(sys.argv[2], years)
        print(f"({sys.argv[2]}: {prices.size} closes via yfinance)")
    elif len(sys.argv) > 1:
        prices = np.loadtxt(sys.argv[1], delimiter=",")
        years = 10.0
    else:
        rng = np.random.default_rng(7)
        prices = 100 * np.exp(np.cumsum(rng.normal(0.0003, 0.010, size=2520)))
        years = 10.0
        print("(no CSV given — calibrating on a synthetic random-walk series)")

    rets = log_returns(prices)
    vol = rolling_volatility(rets, window=40)
    p = estimate_cir_params(vol)
    mu = annualized_drift(prices, years)
    print(f"CIRParams(a={p.a:.6f}, b={p.b:.6f}, c={p.c:.6f})")
    print(f"mu = {mu:.5f}, sigma0 = {float(vol[-1]):.5f}")

    # sanity simulation (Extra#9): CIR vol paths via the pension SV kernel
    traj = simulate_pension(
        jnp.arange(1024, dtype=jnp.uint32), TimeGrid(10.0, 1000),
        y0=1.0, mu=mu, l0=0.01, mort_c=0.075, eta=0.000597, n0=1e4,
        sv=True, v0=float(vol[-1]), cir_a=p.a, cir_b=p.b, cir_c=p.c,
        store_every=100,
    )
    v = traj["v"]
    print(f"E[v(T)] = {float(v[:, -1].mean()):.5f} (long-run mean b = {p.b:.5f})")


if __name__ == "__main__":
    main()
