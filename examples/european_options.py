"""European-option hedge — parity example for ``European Options.ipynb``.

Reference run (Euro#3): S0=K=100, r=8%, sigma=15%, T=1y, 4096 Sobol paths,
weekly rebalancing, MSE-only training normalised by S0. Reference outputs to
compare (Euro#18/#20(out)): V0=11.352 vs discounted payoff 10.479;
phi0=0.10456, psi0=0.89544 (normalised holdings, reported as-is);
Black-Scholes ~10.39.

Run: env -u PALLAS_AXON_POOL_IPS python examples/european_options.py [--paths 4096]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import argparse

from orp_tpu.api import EuropeanConfig, SimConfig, TrainConfig, european_hedge


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--paths", type=int, default=4096)
    ap.add_argument("--option-type", choices=["call", "put"], default="call")
    args = ap.parse_args()

    res = european_hedge(
        EuropeanConfig(option_type=args.option_type),
        SimConfig(n_paths=args.paths, T=1.0, dt=1 / 364, rebalance_every=7),
        TrainConfig(dual_mode="mse_only"),
    )
    print(res.report.summary())
    print(f"\nper-date 99% VaR (first 5): {res.report.var_by_date[:5, 1]}")
    print(f"train loss head/tail: {res.report.train_loss[:2]} ... {res.report.train_loss[-2:]}")


if __name__ == "__main__":
    main()
