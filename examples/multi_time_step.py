"""Dynamic pension-liability hedge — parity example for ``Multi Time Step.ipynb``
and ``Replicating_Portfolio(params)`` (RP.py:29-235).

Reference outputs to compare (Multi#23/#25/#26(out)): V0=981,038 EUR,
phi0=643,687 / psi0=350,888, VaR99=54.38 EUR; sigma sweep table at Multi#30.

Run: env -u PALLAS_AXON_POOL_IPS python examples/multi_time_step.py [--sweep] [--sv]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import argparse

from orp_tpu.api import (
    HedgeRunConfig,
    SimConfig,
    StochVolConfig,
    TrainConfig,
    pension_hedge,
    sigma_sweep,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--paths", type=int, default=4096)
    ap.add_argument("--sweep", action="store_true", help="Multi#29-30 sigma sweep")
    ap.add_argument("--sv", action="store_true", help="RP_SV stochastic-vol variant")
    ap.add_argument("--shared", action="store_true",
                    help="reference-parity mode: the RP.py:172 accidental weight "
                         "sharing + the RP.py:114 phi-combine sign (closest match "
                         "to Multi#25-26(out); see PARITY.md)")
    args = ap.parse_args()

    cfg = HedgeRunConfig(
        sv=StochVolConfig() if args.sv else None,
        # RP defaults: T=10y, dt=1/100, quarterly rebalancing -> 40 dates
        sim=SimConfig(n_paths=args.paths, T=10.0, dt=0.01, rebalance_every=25),
        # default: dual separate models (intended semantics), 500/100 epochs, i=0.1
        train=TrainConfig(dual_mode="shared", holdings_combine="py")
        if args.shared else TrainConfig(),
    )
    if args.sweep:
        rows = sigma_sweep([0.05, 0.10, 0.15, 0.20, 0.30], cfg)
        print(f"{'sigma':>6} {'phi0':>12} {'psi0':>12} {'total':>12}")
        for r in rows:
            print(f"{r['sigma']:6.2f} {r['phi']:12,.0f} {r['psi']:12,.0f} {r['total']:12,.0f}")
    else:
        res = pension_hedge(cfg)
        print(res.report.summary())


if __name__ == "__main__":
    main()
