"""Static (one-step) pension hedge — parity example for ``Single Time Step.ipynb``.

The reference trains both models from scratch for one 10y rebalance interval
(8192 paths, monthly fine grid reduced to {0, T}) and reports (Single#23-24):
phi0=819,539 stocks / psi0=257,308 bonds, V0=1,076,847 EUR.

Run: env -u PALLAS_AXON_POOL_IPS python examples/single_time_step.py
"""

from orp_tpu.api import HedgeRunConfig, SimConfig, TrainConfig, pension_hedge


def main():
    n_steps = 120  # monthly over 10y (Single#5: dt=1/12)
    cfg = HedgeRunConfig(
        sim=SimConfig(n_paths=8192, T=10.0, dt=10.0 / n_steps, rebalance_every=n_steps),
        # one date -> only the from-scratch 500-epoch phase runs. The
        # reference's `cost_of_capital = 0.1*dt` (Single#16) executes AFTER the
        # grid reduction rescales dt to the 10y interval (Single#11:
        # `dt = dt*reduction`), so i = 0.1*10 = 1.0 — the combine collapses to
        # the PURE quantile model (V0 = h, phi = phi2), which is what the
        # recorded 1,076,847 / 819,539 / 257,308 are
        train=TrainConfig(cost_of_capital=1.0),
    )
    res = pension_hedge(cfg)
    print(res.report.summary())


if __name__ == "__main__":
    main()
