"""Static (one-step) pension hedge — parity example for ``Single Time Step.ipynb``.

The reference trains both models from scratch for one 10y rebalance interval
(8192 paths, monthly fine grid reduced to {0, T}) and reports (Single#23-24):
phi0=819,539 stocks / psi0=257,308 bonds, V0=1,076,847 EUR.

Run: env -u PALLAS_AXON_POOL_IPS python examples/single_time_step.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from orp_tpu.api import pension_hedge
from tools.parity_runs import single_step_cfg  # ONE config definition shared
# with the measurement battery and the golden pin (incl. the i=1.0 semantics
# of Single#16's post-reduction cost_of_capital; see single_step_cfg)


def main():
    res = pension_hedge(single_step_cfg())
    print(res.report.summary())


if __name__ == "__main__":
    main()
