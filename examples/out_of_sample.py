"""Out-of-sample hedge validation: train once, evaluate on fresh scrambles.

The reference's risk ledgers (residual P&L, VaR) are computed on the SAME
paths the networks trained on (``Replicating_Portfolio.py:224`` reuses the
training inputs). This example shows the framework-native counterpart:
``european_hedge`` trains the weekly hedge, then ``european_oos`` replays the
per-date trained parameters on paths from a fresh Owen scramble — same
report, honest numbers. With a 97-param net the two should nearly agree
(nothing to overfit with); a large gap would flag a training pathology.

Run: python examples/out_of_sample.py  (CPU ok: JAX_PLATFORMS=cpu)
"""

import dataclasses
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from orp_tpu.api import (
    EuropeanConfig,
    SimConfig,
    TrainConfig,
    european_hedge,
    european_oos,
)


def main():
    euro = EuropeanConfig(constrain_self_financing=False)
    sim = SimConfig(n_paths=16384, T=1.0, dt=1 / 364, rebalance_every=7)
    train = TrainConfig(
        dual_mode="mse_only", epochs_first=120, epochs_warm=30,
        batch_size=2048, lr=1e-3, fused=True, shuffle="blocks",
    )

    trained = european_hedge(euro, sim, train)
    print("=== in-sample (training paths) ===")
    print(trained.report.summary())

    fresh = european_oos(
        trained, euro, dataclasses.replace(sim, seed_fund=2026), train
    )
    print("\n=== out-of-sample (fresh Owen scramble) ===")
    print(fresh.report.summary())

    ins, oos = trained.report, fresh.report
    print(
        f"\nhedge-residual std  in-sample {ins.residual_stats['std']:.4f}"
        f" vs OOS {oos.residual_stats['std']:.4f}"
        f"\nCV price            in-sample {ins.v0_cv:.4f} vs OOS {oos.v0_cv:.4f}"
        f"\nOLS-martingale      in-sample {ins.v0_acv:.4f} vs OOS {oos.v0_acv:.4f}"
    )


if __name__ == "__main__":
    main()
