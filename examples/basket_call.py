"""Multi-asset basket-call hedge — the BASELINE.json config-5 shape.

No reference-notebook analogue (the reference is single-asset only): this is
the framework's multi-asset extension of ``European Options.ipynb``. Prices a
5-asset equally-weighted basket call two ways and compares both to the
moment-matched-lognormal oracle (orp_tpu/utils/basket.py):

  - hedge with the tradeable basket + bond (2-instrument, reference-shaped)
  - hedge with every asset + bond (vector hedge: lower CV variance)

Run: env -u PALLAS_AXON_POOL_IPS python examples/basket_call.py [--paths 131072]
"""

import argparse
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from orp_tpu.api import BasketConfig, SimConfig, TrainConfig, basket_hedge


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--paths", type=int, default=1 << 17)
    ap.add_argument("--vector", action="store_true",
                    help="hedge per-asset (instruments='assets')")
    args = ap.parse_args()

    res = basket_hedge(
        BasketConfig(),
        SimConfig(n_paths=args.paths, T=1.0, dt=1 / 52, rebalance_every=1),
        TrainConfig(
            dual_mode="mse_only", epochs_first=150, epochs_warm=40,
            batch_size=max(args.paths // 32, 512), lr=1e-3,
            fused=True, shuffle="blocks",
        ),
        instruments="assets" if args.vector else "basket",
    )
    r = res.report
    print(r.summary())
    print(f"mm-lognormal oracle = {r.oracle_mm:,.4f}  "
          f"(v0_cv {r.v0_cv:,.4f}, {(r.v0_cv - r.oracle_mm) / r.oracle_mm * 1e4:+.1f} bp "
          "incl. the oracle's own ~20bp Levy approximation error)")
    print(f"cv_std = {r.cv_std:.4f}  "
          f"({'vector' if args.vector else 'basket'} hedge)")


if __name__ == "__main__":
    main()
