"""Option analytics beyond the reference: greeks, early exercise, surfaces,
path-dependent payoffs.

Five capabilities the reference cannot express (its NumPy loops are not
differentiable, its walk never exercises, each notebook run prices one
hard-coded (K, T) point, and it knows only terminal payoffs), each
validated against an independent oracle:

1. Pathwise-AD greeks of the European call (``risk/greeks.py``) vs the
   closed-form Black-Scholes greeks.
2. A Bermudan put via Longstaff-Schwartz LSM (``train/lsm.py``) vs the CRR
   binomial tree — the Longstaff-Schwartz 2001 Table-1 config.
3. The implied-vol surface from ONE Sobol path set (``risk/surface.py``) —
   flat-vol dynamics must give back a flat smile.
4. An arithmetic-Asian call (``risk/asian.py``) whose geometric control
   variate both cuts the Monte-Carlo error ~29x and pins the pipeline to
   an exact lognormal closed form.
5. Brownian-bridge exotics (``risk/barrier.py``, ``risk/lookback.py``):
   barrier survival weights and exact running-max sampling make both
   pricers unbiased for CONTINUOUS monitoring from a 13-knot grid,
   landing on their reflection / Conze-Viswanathan closed forms where
   naive knot-checks are percent-level biased.

Run: env -u PALLAS_AXON_POOL_IPS python examples/option_analytics.py [--paths 65536]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--paths", type=int, default=1 << 16)
    args = ap.parse_args()

    from orp_tpu.risk import european_greeks, price_surface
    from orp_tpu.train.lsm import bermudan_lsm
    from orp_tpu.utils import bs_greeks, crr_price

    print("1) pathwise-AD greeks (Euro call, S0=K=100, r=8%, sigma=15%, T=1)")
    g = european_greeks(args.paths, 100.0, 100.0, 0.08, 0.15, 1.0, n_steps=52)
    oracle = bs_greeks(100.0, 100.0, 0.08, 0.15, 1.0)
    print(f"   {'':<7}{'pathwise-AD':>12}{'Black-Scholes':>15}")
    for name in ("price", "delta", "gamma", "vega", "theta"):
        print(f"   {name:<7}{g.as_dict()[name]:>12.4f}{oracle[name]:>15.4f}")

    print("2) Bermudan put via LSM (LS2001: S0=36, K=40, r=6%, sigma=20%)")
    b = bermudan_lsm(args.paths, 36.0, 40.0, 0.06, 0.2, 1.0, n_exercise=50)
    crr = crr_price(36.0, 40.0, 0.06, 0.2, 1.0, exercise="bermudan",
                    n_steps=5000, exercise_every=100)
    print(f"   LSM {b['price']:.4f} ± {b['se']:.4f}  |  CRR tree {crr:.4f}  "
          f"|  European {b['european']:.4f}  "
          f"(premium {b['early_exercise_premium']:.4f})")

    print("3) implied-vol surface from one path set (flat smile expected)")
    surf = price_surface(args.paths, 100.0, 0.08, 0.15,
                         strikes=[90.0, 100.0, 110.0], T=1.0,
                         n_maturities=4, steps_per_maturity=13)
    iv = np.asarray(surf["iv"])
    for i, t in enumerate(np.asarray(surf["times"])):
        row = "  ".join(f"{v:.4f}" for v in iv[i])
        print(f"   T={t:.2f}:  {row}")
    flat = np.nanmax(np.abs(iv - 0.15))
    print(f"   max |iv - 0.15| = {flat:.4f} (input sigma recovered)")

    print("4) arithmetic-Asian call with geometric control variate")
    from orp_tpu.risk import asian_call_qmc

    a = asian_call_qmc(args.paths, 100.0, 100.0, 0.08, 0.15, 1.0)
    ratio = (f"({a['se_plain'] / a['se']:.0f}x noisier)"
             if a["se"] > 0 else "")
    print(f"   controlled {a['price']:.4f} ± {a['se']:.5f}  |  plain "
          f"{a['plain']:.4f} ± {a['se_plain']:.5f}  {ratio}")
    print(f"   geometric leg: sample {a['geo_sample']:.4f} vs closed form "
          f"{a['geo_closed']:.4f}")

    print("5) bridge exotics at a COARSE 13-knot grid (continuous-monitoring "
          "oracles)")
    from orp_tpu.risk import (
        down_and_out_call,
        down_and_out_call_qmc,
        lookback_call_fixed,
        lookback_call_qmc,
    )

    bar = down_and_out_call_qmc(args.paths, 100.0, 100.0, 90.0, 0.08, 0.25,
                                1.0, n_monitor=13)
    nb = down_and_out_call_qmc(args.paths, 100.0, 100.0, 90.0, 0.08, 0.25,
                               1.0, n_monitor=13, bridge=False)
    print(f"   down-and-out: bridge {bar['price']:.4f} vs closed "
          f"{down_and_out_call(100.0, 100.0, 90.0, 0.08, 0.25, 1.0):.4f} "
          f"(naive reads {nb['price']:.4f})")
    lb = lookback_call_qmc(args.paths, 100.0, 110.0, 0.08, 0.25, 1.0,
                           n_monitor=13)
    print(f"   lookback:     bridge {lb['price']:.4f} vs closed "
          f"{lookback_call_fixed(100.0, 110.0, 0.08, 0.25, 1.0):.4f}")


if __name__ == "__main__":
    main()
