"""North-star benchmark (BASELINE.json): 1M-path, 52-step European-call hedge
converging to Black-Scholes within ±1bp, single chip, wall-clocked end-to-end.

Emits one JSON line:
  {"bs", "v0_acv", "bp_err", "acv_std", "v0_cv", "bp_err_cv", "cv_std",
   "wall_s", "paths", "v0_network"}

The framework-native price (and the ``bp_err`` headline) is ``v0_acv``, the
OLS-martingale-controlled QMC estimator (risk/controls.py) — seed-robust to
~0.1-0.4bp at 1M paths. SCHEMA NOTE: in BENCH_r01/r02 records ``bp_err``
measured the plain hedged-CV estimator, kept here as ``bp_err_cv``
(its error is a ~1-2bp per-seed draw; SCALING.md §3b). The
network-predicted ``v0_network`` reproduces the reference's biased
estimator. Training is deliberately light — both unbiased estimators'
means do not depend on hedge quality, only their variance does.
"""

import json
import pathlib
import sys
import time

# repo-root import without touching PYTHONPATH (the ambient PYTHONPATH carries
# the TPU plugin's sitecustomize and must not be overridden)
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from orp_tpu.api import EuropeanConfig, SimConfig, TrainConfig, european_hedge
from orp_tpu.utils import bs_call


def main(n_paths=1 << 20, epochs_first=120, epochs_warm=30, batch_div=64,
         final_solve=False, lr=1e-3, optimizer="gauss_newton",
         gn_iters=(150, 75), gn_block_rows=1 << 14, quiet=False):
    from orp_tpu.aot import enable_persistent_cache

    # the helper honours the ORP_TESTS_NO_COMPILE_CACHE kill-switch
    # (tests/conftest.py documents the XLA serialize fault it debugs), so an
    # in-suite call of this entry cannot re-enable the cache for the rest of
    # the run; default dir is the repo .jax_cache, env-overridable
    enable_persistent_cache()
    t0 = time.perf_counter()
    res = european_hedge(
        EuropeanConfig(constrain_self_financing=False),
        SimConfig(n_paths=n_paths, T=1.0, dt=1 / 364, rebalance_every=7),
        TrainConfig(
            dual_mode="mse_only",
            # optimizer="gauss_newton" (the default): LM-damped full-batch GN
            # — 150 + 51x75 = 3,975 SEQUENTIAL steps for the whole walk vs
            # the Adam config's 105,600 latency-bound minibatch steps, at
            # identical headline (OLS-martingale) accuracy and BETTER hedge
            # quality than even the deep-Adam frontier trajectory (measured
            # VERBATIM at 1M: acv -0.067bp, cv_std 2.442, VaR99 1.299 —
            # GN_QUALITY_r4.jsonl row gn_150_75_block16k_1M_cpu_f32;
            # SCALING.md §3c-bis). Adam remains available via
            # optimizer="adam" with the epochs knobs.
            optimizer=optimizer,
            gn_iters_first=gn_iters[0],
            gn_iters_warm=gn_iters[1],
            # blocked Gram accumulation (default 16k rows): O(block*P) fit
            # memory; matched-config measurement 2.5x faster on CPU at equal
            # quality, composes with the path mesh (SCALING.md §3e). The
            # strict divisibility guard lives in GNConfig; this benchmark
            # wrapper degrades to one-shot for non-dividing path counts so
            # main(n_paths=...) keeps accepting any size
            gn_block_rows=(
                gn_block_rows
                if gn_block_rows and n_paths % gn_block_rows == 0 else None
            ),
            epochs_first=epochs_first,
            epochs_warm=epochs_warm,
            batch_size=max(n_paths // batch_div, 512),
            lr=lr,
            fused=True,          # whole walk = one XLA program, no per-date dispatch
            shuffle="blocks",    # zero-copy shuffle at 16k-row batches
            final_solve=final_solve,  # closed-form shrunk readout after each
            # MSE fit — neutral at this well-trained default, pays when
            # epochs are cut (SCALING.md §3a)
        ),
    )
    wall = time.perf_counter() - t0
    bs, _ = bs_call(100.0, 100.0, 0.08, 0.15, 1.0)
    out = {
        "bs": round(bs, 6),
        # headline: the OLS-martingale-controlled price (risk/controls.py) —
        # per-date basis regression on top of the learned hedge; its error at
        # 1M paths is ~0.1-0.4bp robustly vs the plain hedged-CV's ~1-2bp
        # seed draw (SCALING.md §3b)
        "v0_acv": round(res.report.v0_acv, 6),
        "bp_err": round((res.report.v0_acv - bs) / bs * 1e4, 3),
        "acv_std": round(res.report.acv_std, 4),
        "v0_cv": round(res.report.v0_cv, 6),
        "bp_err_cv": round((res.report.v0_cv - bs) / bs * 1e4, 3),
        "cv_std": round(res.report.cv_std, 4),
        "wall_s": round(wall, 1),
        "paths": n_paths,
        "v0_network": round(res.v0, 4),
        # the hedge-quality ledger headline: overall 99% VaR of the
        # replication residuals (risk/analytics.py) — published so optimizer
        # trades (GN iteration count vs Adam) are recorded, not just priced
        "var99_overall": round(
            float(res.report.var_overall[res.report.var_qs.index(0.99)]), 4
        ),
    }
    if not quiet:
        print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
