"""The five BASELINE.json benchmark configs as runnable entries.

Each returns a dict of headline numbers; ``python benchmarks/baseline_configs.py
[n]`` runs config n (default: all) and prints one JSON line per config.

1. Single-time-step European call, GBM, 10k Sobol paths  (Single Time Step shape)
2. Multi-time-step European call, 52 rebalance steps, 100k paths
3. European put + call, 1M paths, put-call parity of learned t=0 price
4. Heston stochastic-vol paths, 52-step hedge
5. 5-asset correlated-GBM basket call, 1M paths (path-sharded over the mesh)
"""

import json
import pathlib
import sys
from math import exp

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax.numpy as jnp
import numpy as np

from orp_tpu.utils import bs_call as _bs_call


def bs_call(s0, k, r, sigma, T):
    return _bs_call(s0, k, r, sigma, T)[0]


FAST = dict(dual_mode="mse_only", epochs_first=150, epochs_warm=40, lr=1e-3,
            fused=True, shuffle="blocks")  # single-program walk + zero-copy
# shuffle: the benched single-chip fast path (see SCALING.md). config_5 is the
# one config that may run under a mesh: it overrides fused there (the mesh
# walk is benchmarked through the host-loop programs, as in the device sweep)


def config_1_single_step():
    """European call, ONE rebalance over 1y, 10k-ish Sobol paths."""
    from orp_tpu.api import EuropeanConfig, SimConfig, TrainConfig, european_hedge

    res = european_hedge(
        EuropeanConfig(constrain_self_financing=False),
        SimConfig(n_paths=1 << 13, T=1.0, dt=1 / 364, rebalance_every=364),
        TrainConfig(batch_size=1 << 11, **FAST),
    )
    bs = bs_call(100, 100, 0.08, 0.15, 1.0)
    return {
        "config": "single_step_call_8k",
        "v0_cv": round(res.report.v0_cv, 4),
        "bp_err": round((res.report.v0_cv - bs) / bs * 1e4, 2),
    }


def config_2_multi_step_100k():
    """52-step weekly hedge at 100k paths."""
    from orp_tpu.api import EuropeanConfig, SimConfig, TrainConfig, european_hedge

    res = european_hedge(
        EuropeanConfig(constrain_self_financing=False),
        SimConfig(n_paths=1 << 17, T=1.0, dt=1 / 364, rebalance_every=7),
        TrainConfig(batch_size=1 << 14, **FAST),
    )
    bs = bs_call(100, 100, 0.08, 0.15, 1.0)
    return {
        "config": "multi_step_call_131k",
        "v0_cv": round(res.report.v0_cv, 4),
        "bp_err": round((res.report.v0_cv - bs) / bs * 1e4, 2),
        "cv_std": round(res.report.cv_std, 3),
    }


def config_3_put_call_parity(n_paths=1 << 20):
    """Learned t=0 call and put at 1M paths: check C - P = S0 - K e^{-rT}."""
    from orp_tpu.api import EuropeanConfig, SimConfig, TrainConfig, european_hedge

    sim = SimConfig(n_paths=n_paths, T=1.0, dt=1 / 364, rebalance_every=7)
    train = TrainConfig(batch_size=max(n_paths // 8, 512), **FAST)
    call = european_hedge(EuropeanConfig(constrain_self_financing=False), sim, train)
    put = european_hedge(
        EuropeanConfig(option_type="put", constrain_self_financing=False), sim, train
    )
    parity_true = 100.0 - 100.0 * exp(-0.08)
    parity_learned = call.report.v0_cv - put.report.v0_cv
    return {
        "config": f"put_call_parity_{n_paths // 1000}k",
        "call_cv": round(call.report.v0_cv, 4),
        "put_cv": round(put.report.v0_cv, 4),
        "parity_err_bp": round((parity_learned - parity_true) / 100.0 * 1e4, 2),
    }


def config_4_heston():
    """Heston SV paths + 52-step hedge on the simulated S."""
    from orp_tpu.sde import TimeGrid, bond_curve, payoffs, simulate_heston_log
    from orp_tpu.models import HedgeMLP
    from orp_tpu.train import BackwardConfig, backward_induction

    n = 1 << 16
    grid = TimeGrid(1.0, 364)
    traj = simulate_heston_log(
        jnp.arange(n, dtype=jnp.uint32), grid,
        s0=100.0, mu=0.08, v0=0.0225, kappa=1.5, theta=0.0225, xi=0.25, rho=-0.6,
        seed=1235, store_every=7,
    )
    s = traj["S"]
    b = bond_curve(grid.reduced(7), 0.08)
    payoff = payoffs.call(s[:, -1], 100.0)
    model = HedgeMLP(n_features=1)
    res = backward_induction(
        model, (s / 100.0)[:, :, None], s / 100.0, b / 100.0, payoff / 100.0,
        BackwardConfig(batch_size=1 << 13, **FAST),
        bias_init=(float(payoff.mean()) / 100.0, 0.0),
    )
    # unbiased QMC price under the risk-neutral Heston sim, vs the
    # characteristic-function oracle (orp_tpu/utils/heston.py)
    disc = jnp.exp(-0.08 * jnp.asarray(np.asarray(grid.reduced(7).times())))
    d_mart = disc[1:] * s[:, 1:] - disc[:-1] * s[:, :-1]
    cv = disc[-1] * payoff - jnp.sum(res.phi * d_mart, axis=1)
    from orp_tpu.utils.heston import heston_call

    oracle = heston_call(100.0, 100.0, 0.08, 1.0, v0=0.0225, kappa=1.5,
                         theta=0.0225, xi=0.25, rho=-0.6)
    v0_cv = float(cv.mean())
    return {
        "config": "heston_52step_65k",
        "v0_cv": round(v0_cv, 4),
        "oracle_cf": round(float(oracle), 4),
        "cf_err_bp": round(float((v0_cv - oracle) / oracle * 1e4), 2),
        "cv_std": round(float(cv.std()), 3),
        "v0_network": round(float(res.v0.mean()) * 100.0, 4),
    }


def config_5_basket(n_paths=1 << 20):
    """5-asset correlated-GBM basket-call HEDGE at 1M paths: the trained
    (n_features=5) net hedging with (basket, bond), CV price vs the
    moment-matched-lognormal oracle (orp_tpu/utils/basket.py)."""
    from orp_tpu.api import BasketConfig, SimConfig, TrainConfig, basket_hedge
    from orp_tpu.parallel import make_mesh

    mesh = make_mesh() if len(__import__("jax").devices()) > 1 else None
    basket = BasketConfig()
    res = basket_hedge(
        basket,
        SimConfig(n_paths=n_paths, T=1.0, dt=1 / 52, rebalance_every=1),
        TrainConfig(
            batch_size=max(n_paths // 64, 512),
            **{**FAST, "fused": mesh is None},
        ),
        mesh=mesh,
    )
    r = res.report
    return {
        "config": f"basket5_call_hedge_{n_paths // 1000}k",
        "v0_cv": round(r.v0_cv, 4),
        "oracle_mm": round(r.oracle_mm, 4),
        "mm_diff_bp": round((r.v0_cv - r.oracle_mm) / r.oracle_mm * 1e4, 2),
        "cv_std": round(r.cv_std, 4),
        "v0_plain": round(r.v0_plain, 4),
    }


CONFIGS = [
    config_1_single_step,
    config_2_multi_step_100k,
    config_3_put_call_parity,
    config_4_heston,
    config_5_basket,
]


if __name__ == "__main__":
    picks = [int(a) for a in sys.argv[1:]] or range(1, len(CONFIGS) + 1)
    for i in picks:
        print(json.dumps(CONFIGS[i - 1]()))
