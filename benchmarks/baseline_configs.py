"""The five BASELINE.json benchmark configs as runnable entries.

Each returns a dict of headline numbers; ``python benchmarks/baseline_configs.py
[n]`` runs config n (default: all) and prints one JSON line per config.

1. Single-time-step European call, GBM, 10k Sobol paths  (Single Time Step shape)
2. Multi-time-step European call, 52 rebalance steps, 100k paths
3. European put + call, 1M paths, put-call parity of learned t=0 price
4. Heston stochastic-vol paths, 52-step hedge
5. 5-asset correlated-GBM basket call, 1M paths (path-sharded over the mesh)
"""

import json
import pathlib
import sys
from math import exp

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax.numpy as jnp
import numpy as np

from orp_tpu.utils import bs_call as _bs_call


def bs_call(s0, k, r, sigma, T):
    return _bs_call(s0, k, r, sigma, T)[0]


FAST = dict(dual_mode="mse_only", epochs_first=150, epochs_warm=40, lr=1e-3,
            fused=True, shuffle="blocks")  # single-program walk + zero-copy
# shuffle: the benched single-chip fast path (see SCALING.md). config_5 is the
# one config that may run under a mesh: it overrides fused there (the mesh
# walk is benchmarked through the host-loop programs, as in the device sweep)


def config_1_single_step():
    """European call, ONE rebalance over 1y, 10k-ish Sobol paths."""
    from orp_tpu.api import EuropeanConfig, SimConfig, TrainConfig, european_hedge

    res = european_hedge(
        EuropeanConfig(constrain_self_financing=False),
        SimConfig(n_paths=1 << 13, T=1.0, dt=1 / 364, rebalance_every=364),
        TrainConfig(batch_size=1 << 11, **FAST),
    )
    bs = bs_call(100, 100, 0.08, 0.15, 1.0)
    return {
        "config": "single_step_call_8k",
        "v0_cv": round(res.report.v0_cv, 4),
        "bp_err": round((res.report.v0_cv - bs) / bs * 1e4, 2),
    }


def config_2_multi_step_100k():
    """52-step weekly hedge at 100k paths."""
    from orp_tpu.api import EuropeanConfig, SimConfig, TrainConfig, european_hedge

    res = european_hedge(
        EuropeanConfig(constrain_self_financing=False),
        SimConfig(n_paths=1 << 17, T=1.0, dt=1 / 364, rebalance_every=7),
        TrainConfig(batch_size=1 << 14, **FAST),
    )
    bs = bs_call(100, 100, 0.08, 0.15, 1.0)
    return {
        "config": "multi_step_call_131k",
        "v0_cv": round(res.report.v0_cv, 4),
        "bp_err": round((res.report.v0_cv - bs) / bs * 1e4, 2),
        "cv_std": round(res.report.cv_std, 3),
    }


def config_3_put_call_parity(n_paths=1 << 20):
    """Learned t=0 call and put at 1M paths: check C - P = S0 - K e^{-rT}."""
    from orp_tpu.api import EuropeanConfig, SimConfig, TrainConfig, european_hedge

    sim = SimConfig(n_paths=n_paths, T=1.0, dt=1 / 364, rebalance_every=7)
    train = TrainConfig(batch_size=max(n_paths // 8, 512), **FAST)
    call = european_hedge(EuropeanConfig(constrain_self_financing=False), sim, train)
    put = european_hedge(
        EuropeanConfig(option_type="put", constrain_self_financing=False), sim, train
    )
    parity_true = 100.0 - 100.0 * exp(-0.08)
    parity_learned = call.report.v0_cv - put.report.v0_cv
    return {
        "config": f"put_call_parity_{n_paths // 1000}k",
        "call_cv": round(call.report.v0_cv, 4),
        "put_cv": round(put.report.v0_cv, 4),
        "parity_err_bp": round((parity_learned - parity_true) / 100.0 * 1e4, 2),
    }


HESTON4 = dict(s0=100.0, mu=0.08, v0=0.0225, kappa=1.5, theta=0.0225,
               xi=0.25, rho=-0.6)


def heston4_oracle():
    """CF-oracle price of the battery's ATM call under HESTON4 (shared by
    config_4, the `heston_qe` measurement stage, and anything else that pins
    against this config — one definition, no silent desync)."""
    from orp_tpu.utils.heston import heston_call

    return heston_call(100.0, 100.0, HESTON4["mu"], 1.0, **{
        k: v for k, v in HESTON4.items() if k not in ("s0", "mu")})


def heston_price_rqmc(n_paths=1 << 18, n_scrambles=4, n_steps=104, **dyn):
    """Sub-bp pin of the QE scheme vs the CF oracle: RQMC over independent
    Owen scrambles with the discounted-terminal-spot control variate, whose
    mean is EXACTLY s0 under QE-M's martingale correction.

    Why this exists: the hedge's own CV residual keeps the unhedgeable
    variance risk (spot-only features), so its std is ~8 — a ~30 bp SE at
    65k paths that r4 misread as discretization bias (VERDICT r4 weak 2).
    The scramble-to-scramble spread of this estimator resolves ~0.5 bp.
    Returns (mean, se, per-scramble list)."""
    from orp_tpu.sde import TimeGrid, simulate_heston_qe

    p = {**HESTON4, **dyn}
    r, s0 = p["mu"], p["s0"]
    grid = TimeGrid(1.0, n_steps)
    idx = jnp.arange(n_paths, dtype=jnp.uint32)
    disc = exp(-r * grid.T)
    # the exact-mean control rides QE-M's martingale correction, which the
    # kernel only applies when A = K2 + K4/2 <= 0 (it falls back to plain-QE
    # drift for strongly positive rho — see simulate_heston_qe). With the
    # fallback active the control's true mean is O(dt) nonzero and would
    # SHIFT the estimate by c*E[ctrl] while the scramble CI stayed tight —
    # so use the raw payoff mean there (honest CI, just wider).
    from orp_tpu.sde.kernels import qe_mgf_argument

    use_cv = qe_mgf_argument(p["kappa"], p["xi"], p["rho"], grid.dt) <= 0.0
    prices = []
    for seed in range(11, 11 + n_scrambles):
        traj = simulate_heston_qe(idx, grid, seed=seed, store_every=n_steps, **p)
        st = np.asarray(traj["S"][:, -1], np.float64)
        pay = disc * np.maximum(st - 100.0, 0.0)
        if use_cv:
            ctrl = disc * st - s0  # exact zero mean under QE-M
            c = np.cov(pay, ctrl)[0, 1] / np.var(ctrl)
            pay = pay - c * ctrl
        prices.append(float(pay.mean()))
    arr = np.asarray(prices)
    se = float(arr.std(ddof=1) / np.sqrt(n_scrambles)) if n_scrambles > 1 else 0.0
    return float(arr.mean()), se, prices


def config_4_heston(include_rqmc=True):
    """Heston SV paths (Andersen QE-M, 2 substeps per weekly rebalance knot
    — measured -0.4 +/- 0.7 bp vs the CF oracle, where 52-step QE is
    -1.5 bp and the r4 364-step Euler ladder needed 7x the steps) +
    52-step hedge, with the price leg pinned by the RQMC-CI estimator
    above. ``include_rqmc=False`` skips that leg when a dedicated stage
    (``tools/tpu_measure_all.py`` ``heston_qe``) already measures it."""
    from orp_tpu.sde import TimeGrid, bond_curve, payoffs, simulate_heston_qe
    from orp_tpu.models import HedgeMLP
    from orp_tpu.train import BackwardConfig, backward_induction

    n = 1 << 16
    fine = TimeGrid(1.0, 104)
    grid = fine.reduced(2)
    traj = simulate_heston_qe(
        jnp.arange(n, dtype=jnp.uint32), fine, seed=1235, store_every=2,
        **HESTON4)
    s = traj["S"]
    b = bond_curve(grid, 0.08)
    payoff = payoffs.call(s[:, -1], 100.0)
    model = HedgeMLP(n_features=1)
    res = backward_induction(
        model, (s / 100.0)[:, :, None], s / 100.0, b / 100.0, payoff / 100.0,
        BackwardConfig(batch_size=1 << 13, **FAST),
        bias_init=(float(payoff.mean()) / 100.0, 0.0),
    )
    # hedged-CV estimator (kept for hedge-quality continuity with r4; its
    # std carries the unhedgeable variance risk -> ~30 bp SE, see
    # heston_price_rqmc for the estimator that pins the scheme)
    disc = jnp.exp(-0.08 * jnp.asarray(np.asarray(grid.times())))
    d_mart = disc[1:] * s[:, 1:] - disc[:-1] * s[:, :-1]
    cv = disc[-1] * payoff - jnp.sum(res.phi * d_mart, axis=1)
    oracle = heston4_oracle()
    v0_cv = float(cv.mean())
    out = {
        "config": "heston_52step_65k",
        "scheme": "qe_martingale",
        "v0_cv": round(v0_cv, 4),
        "oracle_cf": round(float(oracle), 4),
        "cf_err_bp": round(float((v0_cv - oracle) / oracle * 1e4), 2),
        "cv_std": round(float(cv.std()), 3),
        "hedged_se_bp": round(float(cv.std()) / np.sqrt(n) / oracle * 1e4, 1),
        "v0_network": round(float(res.v0.mean()) * 100.0, 4),
    }
    if include_rqmc:
        rq_mean, rq_se, _ = heston_price_rqmc()
        out.update(
            price_rqmc=round(rq_mean, 4),
            rqmc_err_bp=round((rq_mean - oracle) / oracle * 1e4, 2),
            rqmc_se_bp=round(rq_se / oracle * 1e4, 2),
        )
    return out


def config_5_basket(n_paths=1 << 20):
    """5-asset correlated-GBM basket-call HEDGE at 1M paths: the trained
    (n_features=5) net hedging with (basket, bond), CV price vs the
    moment-matched-lognormal oracle (orp_tpu/utils/basket.py)."""
    from orp_tpu.api import BasketConfig, SimConfig, TrainConfig, basket_hedge
    from orp_tpu.parallel import make_mesh

    mesh = make_mesh() if len(__import__("jax").devices()) > 1 else None
    basket = BasketConfig()
    res = basket_hedge(
        basket,
        SimConfig(n_paths=n_paths, T=1.0, dt=1 / 52, rebalance_every=1),
        TrainConfig(
            batch_size=max(n_paths // 64, 512),
            **{**FAST, "fused": mesh is None},
        ),
        mesh=mesh,
    )
    r = res.report
    return {
        "config": f"basket5_call_hedge_{n_paths // 1000}k",
        "v0_cv": round(r.v0_cv, 4),
        "oracle_mm": round(r.oracle_mm, 4),
        "mm_diff_bp": round((r.v0_cv - r.oracle_mm) / r.oracle_mm * 1e4, 2),
        "cv_std": round(r.cv_std, 4),
        "v0_plain": round(r.v0_plain, 4),
    }


CONFIGS = [
    config_1_single_step,
    config_2_multi_step_100k,
    config_3_put_call_parity,
    config_4_heston,
    config_5_basket,
]


if __name__ == "__main__":
    picks = [int(a) for a in sys.argv[1:]] or range(1, len(CONFIGS) + 1)
    for i in picks:
        print(json.dumps(CONFIGS[i - 1]()))
